"""Model zoo built on the fluid-style layer API (BASELINE configs 1-4)."""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import bert  # noqa: F401
from . import transformer  # noqa: F401
from . import yolov3  # noqa: F401
from . import word2vec  # noqa: F401


def bundled_builders():
    """name -> zero-arg builder for every bundled model, at the tiny
    configs the test suite exercises.  Each builder must run inside a
    ``fluid.program_guard`` and returns ``(feed_vars, fetch_vars)``; the
    training builders include their optimizer, so the returned program
    already contains the grad sub-graph.  Shared by ``tools/proglint.py``
    and ``tests/test_program_verifier.py`` so the lint surface and the
    test surface cannot drift apart."""

    def _mnist_mlp():
        img, label, logits, loss, acc = mnist.build_mlp()
        return [img, label], [loss, acc]

    def _mnist_conv():
        img, label, logits, loss, acc = mnist.build_conv()
        return [img, label], [loss, acc]

    def _resnet18():
        img, label, loss, acc = resnet.build_train(
            depth=18, class_dim=10, image_size=32)
        return [img, label], [loss, acc]

    def _bert_tiny():
        inputs, loss = bert.build_pretrain(bert.BERT_TINY, seq_len=16,
                                           lr=1e-3)
        return list(inputs), [loss]

    def _transformer_tiny():
        cfg = transformer.TransformerConfig(
            src_vocab=64, trg_vocab=64, d_model=32, heads=2, enc_layers=1,
            dec_layers=1, ffn=64, max_len=16)
        feeds, loss = transformer.build_train(cfg, src_len=8, trg_len=8)
        return list(feeds), [loss]

    def _yolov3_tiny():
        img, gt_box, gt_label, loss = yolov3.build_train(
            class_num=3, image_size=64, max_boxes=4, width=4)
        return [img, gt_box, gt_label], [loss]

    def _word2vec():
        words, nextw, cost = word2vec.build_train(dict_size=100)
        return list(words) + [nextw], [cost]

    return {
        "mnist_mlp": _mnist_mlp,
        "mnist_conv": _mnist_conv,
        "resnet18": _resnet18,
        "bert_tiny": _bert_tiny,
        "transformer_tiny": _transformer_tiny,
        "yolov3_tiny": _yolov3_tiny,
        "word2vec": _word2vec,
    }
