"""Model zoo built on the fluid-style layer API (BASELINE configs 1-4)."""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import bert  # noqa: F401
from . import transformer  # noqa: F401
from . import yolov3  # noqa: F401
from . import word2vec  # noqa: F401
