"""SE-ResNeXt for ImageNet (the reference's heavyweight dist-test model:
dist_se_resnext.py / test_parallel_executor_seresnext payloads).

ResNeXt bottleneck (grouped 3x3 conv, cardinality 32) with a
squeeze-and-excitation gate per block; built from the fluid layer API
like the reference model scripts — the grouped conv rides conv2d's
`groups` (XLA feature-group convolution on TPU)."""

import paddle_tpu as fluid

DEPTH_CFG = {
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def _conv_bn(x, filters, size, stride=1, groups=1, act=None,
             is_test=False):
    c = fluid.layers.conv2d(
        x, filters, size, stride=stride, padding=(size - 1) // 2,
        groups=groups, bias_attr=False)
    return fluid.layers.batch_norm(c, act=act, is_test=is_test)


def _squeeze_excitation(x, reduction_ratio=16):
    c = x.shape[1]
    pool = fluid.layers.pool2d(x, pool_type="avg", global_pooling=True)
    pool = fluid.layers.reshape(pool, shape=[0, c])
    squeeze = fluid.layers.fc(pool, max(c // reduction_ratio, 4),
                              act="relu")
    excite = fluid.layers.fc(squeeze, c, act="sigmoid")
    excite = fluid.layers.reshape(excite, shape=[0, c, 1, 1])
    return fluid.layers.elementwise_mul(x, excite, axis=0)


def bottleneck_block(x, filters, stride, cardinality=32, is_test=False):
    conv0 = _conv_bn(x, filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, filters, 3, stride=stride,
                     groups=cardinality, act="relu", is_test=is_test)
    conv2 = _conv_bn(conv1, filters * 2, 1, is_test=is_test)
    scale = _squeeze_excitation(conv2)
    if x.shape[1] != filters * 2 or stride != 1:
        shortcut = _conv_bn(x, filters * 2, 1, stride=stride,
                            is_test=is_test)
    else:
        shortcut = x
    return fluid.layers.relu(
        fluid.layers.elementwise_add(shortcut, scale))


def se_resnext(img, class_dim=1000, depth=50, cardinality=32,
               is_test=False):
    layers_per_stage = DEPTH_CFG[depth]
    x = _conv_bn(img, 64, 7, stride=2, act="relu", is_test=is_test)
    x = fluid.layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                            pool_type="max")
    filters = 128
    for stage, n_blocks in enumerate(layers_per_stage):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage != 0) else 1
            x = bottleneck_block(x, filters, stride,
                                 cardinality=cardinality,
                                 is_test=is_test)
        filters *= 2
    pool = fluid.layers.pool2d(x, pool_type="avg", global_pooling=True)
    pool = fluid.layers.reshape(pool, shape=[0, pool.shape[1]])
    drop = fluid.layers.dropout(pool, dropout_prob=0.2, is_test=is_test)
    return fluid.layers.fc(drop, class_dim, act="softmax")


def build_train(depth=50, class_dim=1000, image_size=224, lr=0.1,
                cardinality=32, is_test=False, amp=False):
    """Training graph inside the current program guard: returns
    (img, label, avg_loss, acc)."""
    img = fluid.layers.data("img", shape=[3, image_size, image_size])
    label = fluid.layers.data("label", shape=[1], dtype="int64")
    prob = se_resnext(img, class_dim=class_dim, depth=depth,
                      cardinality=cardinality, is_test=is_test)
    loss = fluid.layers.cross_entropy(prob, label)
    avg_loss = fluid.layers.mean(loss)
    acc = fluid.layers.accuracy(prob, label)
    if not is_test:
        opt = fluid.optimizer.Momentum(
            learning_rate=lr, momentum=0.9,
            regularization=fluid.regularizer.L2Decay(1e-4))
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(avg_loss)
    return img, label, avg_loss, acc
