"""BackwardStrategy (reference dygraph/backward_strategy.py:17, backed by
the pybind class in imperative.cc with one knob, ``sort_sum_gradient``).

The knob selects deterministic sorted gradient summation in the reference's
autograd engine.  Our tape replays in deterministic reverse-registration
order and sums cotangents in a fixed order already, so both settings are
equivalent here; the class is accepted (and carried by ``backward()``) for
source compatibility."""

__all__ = ["BackwardStrategy"]


class BackwardStrategy:
    def __init__(self):
        self.sort_sum_gradient = False
