"""Imperative (dygraph) mode — eager op execution on jax.Arrays with tape
autograd.  Parity: python/paddle/fluid/dygraph/ + paddle/fluid/imperative/."""

from .base import guard, enabled, to_variable, no_grad, Tracer
from .layers import Layer
from .nn import (
    Conv2D, Conv2DTranspose, Pool2D, FC, Linear, BatchNorm, Embedding,
    LayerNorm, GroupNorm, PRelu, Dropout,
)
from .checkpoint import save_dygraph, load_dygraph
from .container import Sequential
from .backward_strategy import BackwardStrategy
from .jit import TracedLayer
from .parallel import prepare_context, Env, ParallelEnv, DataParallel
from .learning_rate_scheduler import (
    LearningRateDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
    InverseTimeDecay, PolynomialDecay, CosineDecay, NoamDecay)

__all__ = [
    "guard", "enabled", "to_variable", "no_grad", "Tracer", "Layer",
    "Conv2D", "Conv2DTranspose", "Pool2D", "FC", "Linear", "BatchNorm",
    "Embedding", "LayerNorm", "GroupNorm", "PRelu", "Dropout",
    "save_dygraph", "load_dygraph", "TracedLayer",
    "Sequential", "BackwardStrategy",
    "prepare_context", "Env", "ParallelEnv", "DataParallel",
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay",
]
