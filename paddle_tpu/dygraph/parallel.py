"""Dygraph data parallel (reference python/paddle/fluid/dygraph/parallel.py:84).

The reference runs one Python process per GPU with NCCL allreduce of
coalesced gradients.  The TPU-native eager path keeps the same API
(``prepare_context``/``Env``/``DataParallel.scale_loss``/
``apply_collective_grads``) but executes the gradient allreduce as one jitted
``jax.lax.psum`` over the local device mesh when more than one chip is
visible, since per-process eager NCCL has no TPU analog — multi-host dygraph
should graduate to the static `fleet` path (transpiler/collective.py), which
shards via pjit.  With one device everything degenerates to no-ops, which is
also the reference behavior for nranks==1.
"""

import os

import numpy as np

from .. import framework
from .base import no_grad_guard

__all__ = ["prepare_context", "Env", "DataParallel", "ParallelEnv"]


class Env:
    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_gpus", "0"))
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


ParallelEnv = Env


class ParallelStrategy:
    def __init__(self):
        self.nranks = 1
        self.local_rank = 0
        self.trainer_endpoints = []
        self.current_endpoint = ""


def prepare_context(strategy=None):
    """Initialize the eager-mode parallel context from the launcher env
    (analog of imperative/nccl_context.cc:106 — but bootstrap is
    jax.distributed, not a hand-rolled ncclUniqueId TCP exchange)."""
    if strategy is None:
        strategy = ParallelStrategy()
        env = Env()
        strategy.nranks = env.nranks
        strategy.local_rank = env.local_rank
        strategy.trainer_endpoints = env.trainer_endpoints
        strategy.current_endpoint = env.current_endpoint
    if strategy.nranks > 1:
        import jax

        if jax.process_count() == 1:
            try:
                jax.distributed.initialize()
            except Exception:
                pass  # single-host multi-device: no coordinator needed
    return strategy


class DataParallel:
    def __init__(self, layers, strategy=None):
        self._layers = layers
        self._strategy = strategy or prepare_context()

    @property
    def _nranks(self):
        return max(1, self._strategy.nranks)

    def __call__(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict

    def scale_loss(self, loss):
        """loss / nranks before backward (dygraph/parallel.py:150)."""
        if self._nranks <= 1:
            return loss
        from .. import layers

        # the scale stays on the tape so gradients scale too
        return layers.scale(loss, scale=1.0 / self._nranks)

    def apply_collective_grads(self):
        """Allreduce-sum every parameter gradient across ranks
        (dygraph/parallel.py:201).  Local-mesh implementation: grads are
        averaged via a jitted psum when multiple processes are attached;
        single-rank is a no-op."""
        if self._nranks <= 1:
            return
        import jax

        if jax.process_count() != self._nranks:
            raise RuntimeError(
                "dygraph DataParallel with nranks=%d requires a "
                "jax.distributed world of the same size (got %d processes); "
                "use the fleet collective static path for multi-host TPU "
                "training" % (self._nranks, jax.process_count()))
        from jax.experimental import multihost_utils

        for p in self._layers.parameters():
            if p._grad_ivar is None:
                continue
            summed = multihost_utils.process_allgather(
                np.asarray(p._grad_ivar))
            p._grad_ivar = summed.sum(axis=0)

    def clear_gradients(self):
        self._layers.clear_gradients()
