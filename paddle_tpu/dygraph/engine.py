"""Tape-based autograd for dygraph mode.

Functional analog of the reference's ``BasicEngine``
(paddle/fluid/imperative/engine.h:69) + ``GradientAccumulator``
(imperative/gradient_accumulator.cc): instead of running recorded grad
OpBases, each tape entry's forward lowering is replayed under ``jax.vjp``
with its snapshot inputs and original PRNG key, and input cotangents are
accumulated per Variable.  XLA CSE/fusion make the replayed forward cheap
under jit; in eager mode it is the straightforward O(ops) reverse sweep.
"""

import jax
import jax.numpy as jnp

from ..core.lowering import LowerCtx
from ..core.registry import _lower_attrs

__all__ = ["run_backward"]


def _entry_backward(entry, grads):
    """Compute input cotangents for one tape entry.  Returns list of
    (var, grad_array) for differentiable inputs, or None if no output of
    this entry has a gradient."""
    opdef = entry.opdef

    # cotangents per output, flat in (slot, item) order; skip entries whose
    # outputs carry no incoming gradient at all.
    out_cts = []
    any_grad = False
    for slot, recs in entry.out_slots:
        for v, shape, dtype in recs:
            g = grads.get(id(v)) if v is not None else None
            if g is not None:
                any_grad = True
                if g.dtype != dtype:
                    g = g.astype(dtype)
            out_cts.append((g, shape, dtype))
    if not any_grad:
        return None

    # positions of differentiable inputs
    diff_pos = []  # (slot_index, item_index, var)
    for si, (slot, recs) in enumerate(entry.in_slots):
        if slot in opdef.no_grad_inputs:
            continue
        for ii, (v, arr) in enumerate(recs):
            if v is not None and arr is not None and not v.stop_gradient:
                diff_pos.append((si, ii, v))
    if not diff_pos:
        return []

    diff_vals = tuple(entry.in_slots[si][1][ii][1] for si, ii, _ in diff_pos)

    def replay(*dvals):
        # rebuild slot args with the traced values substituted
        subst = {}
        for (si, ii, _), val in zip(diff_pos, dvals):
            subst[(si, ii)] = val
        args = []
        for si, (slot, recs) in enumerate(entry.in_slots):
            vals = [
                subst.get((si, ii), arr)
                for ii, (v, arr) in enumerate(recs)
            ]
            if slot in opdef.duplicable_inputs:
                args.append(vals)
            elif not vals:
                args.append(None)
            else:
                args.append(vals[0])
        ctx = LowerCtx(rng_key=entry.rng_key, mode="eager")
        out = opdef.lower(ctx, *args, **_lower_attrs(entry.attrs))
        if len(opdef.output_slots) == 1 and not isinstance(out, (tuple, list)):
            out = (out,)
        elif isinstance(out, list):
            out = tuple(out)
        if len(opdef.output_slots) == 1 and len(out) != 1:
            out = (list(out),)
        flat = []
        for slot, val in zip(opdef.output_slots, out):
            items = (
                list(val)
                if slot in opdef.duplicable_outputs and val is not None
                else [val]
            )
            for item in items:
                flat.append(item)
        # only outputs that were produced at trace time participate
        return tuple(x for x in flat if x is not None)

    _, vjp_fn = jax.vjp(replay, *diff_vals)
    cts = tuple(
        g if g is not None else jnp.zeros(shape, dtype)
        for g, shape, dtype in out_cts
        if dtype is not None
    )
    in_cts = vjp_fn(cts)
    return [(v, ct) for (_, _, v), ct in zip(diff_pos, in_cts)]


def run_backward(tracer, root, retain_graph=False):
    """Reverse sweep over the tape from ``root`` (a scalar-ish Variable)."""
    if root._ivar is None:
        raise RuntimeError("backward() on a variable with no value")
    grads = {id(root): jnp.ones(root._ivar.shape, root._ivar.dtype)}
    varmap = {id(root): root}

    for entry in reversed(tracer.tape):
        res = _entry_backward(entry, grads)
        if res is None:
            continue
        for v, ct in res:
            k = id(v)
            varmap[k] = v
            prev = grads.get(k)
            grads[k] = ct if prev is None else prev + ct

    # materialize .gradient() on LEAF vars only (params & user-held inputs
    # that no taped op produced): accumulate across backward() calls until
    # clear_gradient(), matching the reference's GradientAccumulator
    # semantics.  Intermediates' cotangents stay local to this sweep so
    # their arrays are freed with `grads`.
    produced = set()
    for entry in tracer.tape:
        for _, recs in entry.out_slots:
            for v, _, _ in recs:
                if v is not None:
                    produced.add(id(v))
    from ..framework import Parameter

    for k, g in grads.items():
        v = varmap[k]
        if k in produced and not isinstance(v, Parameter):
            continue
        if v._grad_ivar is None:
            v._grad_ivar = g
        else:
            v._grad_ivar = v._grad_ivar + g
    if not retain_graph:
        tracer.clear_tape()
