"""Dygraph (imperative) mode: eager per-op execution with tape autograd.

TPU-native analog of the reference's imperative runtime
(paddle/fluid/imperative/tracer.cc:82 Tracer::TraceOp,
python/paddle/fluid/dygraph/base.py:111 guard, :176 to_variable): instead of
dispatching per-op CUDA kernels, each traced op calls its registered JAX
lowering eagerly on concrete ``jax.Array`` values.  Gradients come from a
recorded tape replayed through ``jax.vjp`` (engine.py) — the functional
equivalent of the reference's OpBase grad chain + BasicEngine
(imperative/engine.h:69).
"""

import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from ..core.lowering import LowerCtx
from ..core.registry import get_op_def, _lower_attrs

__all__ = ["guard", "enabled", "to_variable", "no_grad", "Tracer"]


def _as_var_objs(block, v):
    """Normalize a slot value to a list of Variable objects (None allowed)."""
    if v is None:
        return []
    if not isinstance(v, (list, tuple)):
        v = [v]
    out = []
    for x in v:
        if isinstance(x, framework.Variable):
            out.append(x)
        elif isinstance(x, str):
            out.append(block._find_var_recursive(x))
        elif x is None:
            out.append(None)
        else:
            raise TypeError("expected Variable or str, got %r" % (x,))
    return out


class _TapeEntry:
    __slots__ = ("opdef", "attrs", "rng_key", "in_slots", "out_slots")

    def __init__(self, opdef, attrs, rng_key, in_slots, out_slots):
        self.opdef = opdef
        self.attrs = attrs
        self.rng_key = rng_key
        # in_slots: [(slot, [(var|None, array|None), ...]), ...] in
        # opdef.input_slots order; arrays snapshot trace-time values (params
        # mutate in place between forward and backward).
        self.in_slots = in_slots
        # out_slots: [(slot, [(var|None, shape, dtype), ...]), ...]
        self.out_slots = out_slots


class Tracer:
    """Eager op executor + autograd tape (imperative/tracer.cc:82 analog)."""

    def __init__(self, seed=0):
        self._base_key = jax.random.key(seed)
        self._key_n = 0
        self.tape = []
        self._has_grad = True
        self.params = {}  # name -> Parameter created under this tracer
        self.train_mode = True

    # -- rng -----------------------------------------------------------------
    def _next_key(self):
        k = jax.random.fold_in(self._base_key, self._key_n)
        self._key_n += 1
        return k

    # -- parameters ----------------------------------------------------------
    def track_parameter(self, param):
        self.params[param.name] = param

    def all_parameters(self):
        return list(self.params.values())

    # -- op execution --------------------------------------------------------
    def trace_op(self, block, type, inputs=None, outputs=None, attrs=None):
        opdef = get_op_def(type)
        if opdef is None or opdef.lower is None:
            raise NotImplementedError(
                "op %r has no lowering; cannot run in dygraph mode" % type
            )
        op = framework.Operator(block, type, inputs, outputs, attrs)
        opdef.validate(op)
        from ..core.registry import record_executed

        record_executed(type)

        in_objs = {k: _as_var_objs(block, v) for k, v in (inputs or {}).items()}
        out_objs = {k: _as_var_objs(block, v) for k, v in (outputs or {}).items()}

        args = []
        for slot in opdef.input_slots:
            vars_ = in_objs.get(slot, [])
            vals = []
            for v in vars_:
                if v is None:
                    vals.append(None)
                    continue
                if v._ivar is None:
                    if slot in opdef.optional_inputs:
                        vals.append(None)
                        continue
                    raise RuntimeError(
                        "op %s input %s=%s has no value (uninitialized "
                        "variable in dygraph mode)" % (type, slot, v.name)
                    )
                vals.append(v._ivar)
            if slot in opdef.duplicable_inputs:
                args.append(vals)
            elif not vals:
                args.append(None)
            else:
                args.append(vals[0])

        rng_key = self._next_key() if opdef.n_rng else None
        ctx = LowerCtx(rng_key=rng_key, op=op, block=block, mode="eager")
        from ..profiler import RecordEvent

        with RecordEvent(type):
            out = opdef.lower(ctx, *args, **_lower_attrs(op.attrs))
        out = _normalize_outputs(opdef, out)

        from ..flags import flag as _flag

        if _flag("check_nan_inf"):
            for slot, val in zip(opdef.output_slots, out):
                for item in (val if isinstance(val, (list, tuple)) else [val]):
                    if item is None:
                        continue
                    a = np.asarray(item)
                    if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
                        raise RuntimeError(
                            "NaN/Inf in output %s of op %s "
                            "(FLAGS_check_nan_inf)" % (slot, type))

        # does any differentiable input require grad?
        requires = False
        if self._has_grad and opdef.grad_maker is not None:
            for slot in opdef.input_slots:
                if slot in opdef.no_grad_inputs:
                    continue
                for v in in_objs.get(slot, []):
                    if v is not None and not v.stop_gradient:
                        requires = True
                        break
                if requires:
                    break

        out_slots_rec = []
        for slot, val in zip(opdef.output_slots, out):
            vars_ = out_objs.get(slot, [])
            items = (
                list(val) if slot in opdef.duplicable_outputs and val is not None
                else [val]
            )
            recs = []
            for v, item in zip(vars_, items):
                if v is None or item is None:
                    recs.append((None, (), None))
                    continue
                item = jnp.asarray(item)
                v._ivar = item
                v.shape = tuple(item.shape)
                # temp outputs inherit differentiability; Parameters keep
                # their own flag (an eager initializer/optimizer op writing a
                # param must not mark it stop_gradient)
                if not isinstance(v, framework.Parameter):
                    v.stop_gradient = not requires
                recs.append((v, tuple(item.shape), item.dtype))
            out_slots_rec.append((slot, recs))

        # eager mode keeps no graph: drop temp outputs from the block's
        # symbol table so their arrays die with the last user/tape reference
        # (the scratch Program would otherwise pin every step's activations)
        for slot, recs in out_slots_rec:
            for v, _, _ in recs:
                if v is not None and not v.persistable:
                    block.vars.pop(v.name, None)

        if requires:
            in_slots_rec = []
            for slot in opdef.input_slots:
                recs = [
                    (v, v._ivar if v is not None else None)
                    for v in in_objs.get(slot, [])
                ]
                in_slots_rec.append((slot, recs))
            self.tape.append(
                _TapeEntry(opdef, dict(op.attrs), rng_key, in_slots_rec,
                           out_slots_rec)
            )
        return op

    def clear_tape(self):
        self.tape = []


def _normalize_outputs(opdef, out):
    if len(opdef.output_slots) == 1 and not isinstance(out, (tuple, list)):
        out = (out,)
    elif isinstance(out, list):
        out = tuple(out)
    if len(opdef.output_slots) == 1 and len(out) != 1:
        out = (list(out),)
    return out


# ---------------------------------------------------------------------------
# mode switches
# ---------------------------------------------------------------------------


def enabled():
    return framework.in_dygraph_mode()


@contextlib.contextmanager
def guard(place=None, seed=0):
    """Enter dygraph mode (reference dygraph/base.py:111).

    Pushes one scratch Program as BOTH the main and startup program so that
    layer helpers and initializers work unchanged — their appended ops are
    executed eagerly by the tracer instead of accumulating in a graph.
    """
    tracer = Tracer(seed=seed)
    prog = framework.Program()
    with framework.program_guard(prog, prog):
        prev = framework._dygraph_tracer_
        framework._dygraph_tracer_ = tracer
        try:
            yield
        finally:
            framework._dygraph_tracer_ = prev


@contextlib.contextmanager
def no_grad_guard():
    tracer = framework._dygraph_tracer()
    if tracer is None:
        yield
        return
    prev = tracer._has_grad
    tracer._has_grad = False
    try:
        yield
    finally:
        tracer._has_grad = prev


def no_grad(fn=None):
    """Decorator or context manager disabling tape recording."""
    if fn is None:
        return no_grad_guard()

    def wrapper(*args, **kwargs):
        with no_grad_guard():
            return fn(*args, **kwargs)

    return wrapper


def to_variable(value, name=None, zero_copy=None):
    """numpy/jax array -> eager Variable (reference dygraph/base.py:176)."""
    if isinstance(value, framework.Variable):
        return value
    if not framework.in_dygraph_mode():
        raise RuntimeError("to_variable requires dygraph mode (use "
                           "fluid.dygraph.guard())")
    np_val = np.asarray(value)
    arr = jnp.asarray(np_val)
    block = framework.default_main_program().current_block()
    # construct directly (NOT block.create_var): eager tensors are not part
    # of any symbol table — avoids aliasing an existing var of the same name
    # and keeps the scratch block from pinning every input array
    var = framework.Variable(
        block, name=name, shape=arr.shape, dtype=np_val.dtype,
        stop_gradient=True,
    )
    var._ivar = arr
    return var
