"""Dygraph learning-rate schedulers (parity:
python/paddle/fluid/dygraph/learning_rate_scheduler.py).

Each scheduler is a callable whose value advances one step per optimizer
update (the optimizer calls `step()` when it refreshes the lr variable)."""

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = begin
        self.step_size = step

    def __call__(self):
        return float(self.value())

    def step(self):
        val = self.value()
        self.step_num += self.step_size
        return float(val)

    def value(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def value(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def value(self):
        n = self.step_num / self.decay_steps
        if self.staircase:
            n = math.floor(n)
        return self.lr * math.exp(-self.decay_rate * n)


class ExponentialDecay(NaturalExpDecay):
    def value(self):
        n = self.step_num / self.decay_steps
        if self.staircase:
            n = math.floor(n)
        return self.lr * (self.decay_rate ** n)


class InverseTimeDecay(NaturalExpDecay):
    def value(self):
        n = self.step_num / self.decay_steps
        if self.staircase:
            n = math.floor(n)
        return self.lr / (1 + self.decay_rate * n)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.decay_steps = decay_steps
        self.end_lr = end_learning_rate
        self.power = power
        self.cycle = cycle

    def value(self):
        t = self.step_num
        steps = self.decay_steps
        if self.cycle:
            mult = max(math.ceil(t / steps), 1)
            steps = steps * mult
        else:
            t = min(t, steps)
        return ((self.lr - self.end_lr)
                * (1 - t / steps) ** self.power + self.end_lr)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def value(self):
        epoch = self.step_num // self.step_each_epoch
        return self.lr * 0.5 * (math.cos(epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1):
        super().__init__(begin, step)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def value(self):
        n = max(self.step_num, 1)
        return (self.d_model ** -0.5) * min(n ** -0.5,
                                            n * self.warmup_steps ** -1.5)
