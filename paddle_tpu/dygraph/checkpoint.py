"""save_dygraph / load_dygraph (reference dygraph/checkpoint.py).

State dicts are name->ndarray maps saved as a single ``.npz`` (the TPU
build's container format; the reference used per-var LoDTensor streams).
Optimizer state (accumulators) saves the same way.
"""

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]

_SUFFIX = ".pdparams.npz"
_OPT_SUFFIX = ".pdopt.npz"


def save_dygraph(state_dict, model_path):
    """state_dict: from Layer.state_dict() or Optimizer.state_dict()."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + _SUFFIX, **arrays)


def load_dygraph(model_path):
    """Returns (param_state_dict, optimizer_state_dict_or_None)."""
    params = None
    opt = None
    p = model_path + _SUFFIX
    if os.path.exists(p):
        with np.load(p) as z:
            params = {k: z[k] for k in z.files}
    o = model_path + _OPT_SUFFIX
    if os.path.exists(o):
        with np.load(o) as z:
            opt = {k: z[k] for k in z.files}
    if params is None:
        raise ValueError("no checkpoint found at %s" % p)
    return params, opt
