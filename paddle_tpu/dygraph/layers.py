"""Dygraph Layer base class (reference python/paddle/fluid/dygraph/layers.py).

A Layer owns Parameters (created once, initialized eagerly by the tracer) and
sub-layers; ``__call__`` dispatches to ``forward``, which emits ops that the
dygraph tracer executes immediately on jax.Arrays.
"""

import collections

import numpy as np

from .. import framework
from ..layer_helper import LayerHelper
from ..utils import unique_name

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        if name_scope is None:
            name_scope = self.__class__.__name__.lower()
        self._full_name = unique_name.generate(name_scope)
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._helper = LayerHelper(self._full_name)
        self.training = True

    def full_name(self):
        return self._full_name

    # -- parameters ----------------------------------------------------------
    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        return self._helper.create_parameter(attr, shape, dtype, is_bias,
                                             default_initializer)

    def parameters(self, include_sublayers=True):
        ret, seen = [], set()
        for p in self._parameters.values():
            if id(p) not in seen:
                seen.add(id(p))
                ret.append(p)
        if include_sublayers:
            for l in self._sub_layers.values():
                for p in l.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        ret.append(p)
        return ret

    def sublayers(self, include_sublayers=True):
        ret = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.sublayers())
        return ret

    def named_parameters(self, prefix=""):
        for name, p in self._parameters.items():
            yield (prefix + name if not prefix else prefix + "." + name), p
        for lname, l in self._sub_layers.items():
            sub_prefix = prefix + "." + lname if prefix else lname
            yield from l.named_parameters(sub_prefix)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    # -- train/eval ----------------------------------------------------------
    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, include_sublayers=True):
        out = collections.OrderedDict()
        for p in self.parameters(include_sublayers):
            out[p.name] = p.numpy()
        return out

    def set_dict(self, state_dict, include_sublayers=True):
        import jax.numpy as jnp

        for p in self.parameters(include_sublayers):
            if p.name in state_dict:
                val = np.asarray(state_dict[p.name])
                if tuple(val.shape) != tuple(p.shape):
                    raise ValueError(
                        "shape mismatch for %s: checkpoint %s vs param %s"
                        % (p.name, val.shape, p.shape)
                    )
                p._ivar = jnp.asarray(val)
        return self

    load_dict = set_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    # -- attribute magic -----------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, framework.Parameter):
            self._parameters[name] = value
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        params = self.__dict__.get("_parameters")
        if params is not None and name in params:
            return params[name]
        subs = self.__dict__.get("_sub_layers")
        if subs is not None and name in subs:
            return subs[name]
        raise AttributeError(
            "%r object has no attribute %r" % (type(self).__name__, name)
        )
