"""Dygraph stateful layers (reference python/paddle/fluid/dygraph/nn.py).

Each class creates its Parameters ONCE in __init__ (initializer ops run
eagerly through the tracer) and its forward emits the same compute ops as the
static ``layers.*`` builders, executed immediately on jax.Arrays.
"""

from ..initializer import Constant, Normal
from ..layer_helper import LayerHelper
from .layers import Layer

__all__ = [
    "Conv2D", "Conv2DTranspose", "Pool2D", "FC", "Linear", "BatchNorm",
    "Embedding", "LayerNorm", "GroupNorm", "PRelu", "Dropout",
]


class FC(Layer):
    """Fully connected (reference dygraph/nn.py FC): flatten to 2-D + mul +
    bias + act.  Weight is created lazily at first call (input dim unknown
    until then), matching the reference."""

    def __init__(self, name_scope, size, num_flatten_dims=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self._w = None
        self._b = None

    def _build_once(self, input):
        in_features = 1
        for d in input.shape[self._num_flatten_dims:]:
            in_features *= int(d)
        self._w = self.create_parameter(
            attr=self._param_attr, shape=[in_features, self._size],
            dtype=self._dtype)
        self.add_parameter("weight", self._w)
        if self._bias_attr is not False:
            self._b = self.create_parameter(
                attr=self._bias_attr, shape=[self._size], dtype=self._dtype,
                is_bias=True)
            if self._b is not None:
                self.add_parameter("bias", self._b)

    def forward(self, input):
        if self._w is None:
            self._build_once(input)
        h = self._helper
        tmp = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="mul", inputs={"X": [input], "Y": [self._w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": self._num_flatten_dims,
                   "y_num_col_dims": 1})
        if self._b is not None:
            pre = h.create_variable_for_type_inference(self._dtype)
            h.append_op(
                type="elementwise_add", inputs={"X": [tmp], "Y": [self._b]},
                outputs={"Out": [pre]},
                attrs={"axis": self._num_flatten_dims})
            tmp = pre
        return h.append_activation(tmp, self._act)


class Linear(FC):
    """1.7-style Linear(input_dim, output_dim) convenience over FC."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__("linear", output_dim, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, dtype=dtype)
        # eager weight creation: input dim is known
        class _Stub:
            shape = (1, input_dim)
        self._build_once(_Stub())


class Conv2D(Layer):
    def __init__(self, name_scope, num_channels, num_filters, filter_size,
                 stride=1, padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        groups = groups or 1
        if isinstance(filter_size, int):
            filter_size = [filter_size, filter_size]
        self._attrs = {
            "strides": [stride, stride] if isinstance(stride, int) else list(stride),
            "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
            "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
            "groups": groups,
            "data_format": "NCHW",
        }
        self._act = act
        filter_shape = [num_filters, num_channels // groups] + list(filter_size)
        import math

        fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
        self.weight = self.create_parameter(
            attr=param_attr, shape=filter_shape, dtype=dtype,
            default_initializer=Normal(0.0, math.sqrt(2.0 / fan_in)))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[num_filters], dtype=dtype, is_bias=True)

    def forward(self, input):
        h = self._helper
        pre = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="conv2d", inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [pre]}, attrs=dict(self._attrs))
        if self.bias is not None:
            out = h.create_variable_for_type_inference(self._dtype)
            h.append_op(
                type="elementwise_add",
                inputs={"X": [pre], "Y": [self.bias]},
                outputs={"Out": [out]}, attrs={"axis": 1})
            pre = out
        return h.append_activation(pre, self._act)


class Conv2DTranspose(Layer):
    def __init__(self, name_scope, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        groups = groups or 1
        if isinstance(filter_size, int):
            filter_size = [filter_size, filter_size]
        self._attrs = {
            "strides": [stride, stride] if isinstance(stride, int) else list(stride),
            "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
            "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
            "groups": groups,
            "output_size": list(output_size) if output_size else [],
            "data_format": "NCHW",
        }
        self._act = act
        filter_shape = [num_channels, num_filters // groups] + list(filter_size)
        self.weight = self.create_parameter(
            attr=param_attr, shape=filter_shape, dtype=dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[num_filters], dtype=dtype, is_bias=True)

    def forward(self, input):
        h = self._helper
        pre = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="conv2d_transpose",
            inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [pre]}, attrs=dict(self._attrs))
        if self.bias is not None:
            out = h.create_variable_for_type_inference(self._dtype)
            h.append_op(
                type="elementwise_add",
                inputs={"X": [pre], "Y": [self.bias]},
                outputs={"Out": [out]}, attrs={"axis": 1})
            pre = out
        return h.append_activation(pre, self._act)


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True,
                 dtype="float32"):
        super().__init__(name_scope or "pool2d", dtype)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size, pool_size] if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride, pool_stride] if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding, pool_padding] if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": "NCHW",
        }

    def forward(self, input):
        h = self._helper
        out = h.create_variable_for_type_inference(input.dtype)
        h.append_op(type="pool2d", inputs={"X": [input]},
                    outputs={"Out": [out]}, attrs=dict(self._attrs))
        return out


class BatchNorm(Layer):
    def __init__(self, name_scope, num_channels, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", use_global_stats=False):
        super().__init__(name_scope, dtype)
        self._act = act
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self._is_test = is_test
        self.weight = self.create_parameter(
            attr=param_attr, shape=[num_channels], dtype=dtype,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            attr=bias_attr, shape=[num_channels], dtype=dtype, is_bias=True,
            default_initializer=Constant(0.0))
        h = self._helper
        self._mean = h.create_global_variable(
            persistable=True, shape=[num_channels], dtype=dtype)
        self._mean.stop_gradient = True
        Constant(0.0)(self._mean, self._mean.block)
        self._variance = h.create_global_variable(
            persistable=True, shape=[num_channels], dtype=dtype)
        self._variance.stop_gradient = True
        Constant(1.0)(self._variance, self._variance.block)

    def forward(self, input):
        h = self._helper
        saved_mean = h.create_variable_for_type_inference(
            self._dtype, stop_gradient=True)
        saved_var = h.create_variable_for_type_inference(
            self._dtype, stop_gradient=True)
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="batch_norm",
            inputs={"X": [input], "Scale": [self.weight],
                    "Bias": [self.bias], "Mean": [self._mean],
                    "Variance": [self._variance]},
            outputs={"Y": [out], "MeanOut": [self._mean],
                     "VarianceOut": [self._variance],
                     "SavedMean": [saved_mean],
                     "SavedVariance": [saved_var]},
            attrs={"momentum": self._momentum, "epsilon": self._epsilon,
                   "is_test": self._is_test or not self.training,
                   "data_layout": self._data_layout,
                   "use_global_stats": self._use_global_stats})
        return h.append_activation(out, self._act)


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__(name_scope or "embedding", dtype)
        self._size = list(size)
        self._padding_idx = -1 if padding_idx is None else (
            padding_idx if padding_idx >= 0 else size[0] + padding_idx)
        self._is_sparse = is_sparse
        self._is_distributed = is_distributed
        self.weight = self.create_parameter(
            attr=param_attr, shape=self._size, dtype=dtype)

    def forward(self, input):
        h = self._helper
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="lookup_table",
            inputs={"W": [self.weight], "Ids": [input]},
            outputs={"Out": [out]},
            attrs={"is_sparse": self._is_sparse,
                   "is_distributed": self._is_distributed,
                   "padding_idx": self._padding_idx})
        return out


class LayerNorm(Layer):
    def __init__(self, name_scope, scale=True, shift=True, begin_norm_axis=1,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32", normalized_shape=None):
        super().__init__(name_scope, dtype)
        self._begin_norm_axis = begin_norm_axis
        self._epsilon = epsilon
        self._act = act
        self._scale, self._shift = scale, shift
        self._param_attr, self._bias_attr = param_attr, bias_attr
        self.weight = self.bias = None
        if normalized_shape is not None:
            self._build(int(np_prod(normalized_shape)))

    def _build(self, norm_size):
        if self._scale:
            self.weight = self.create_parameter(
                attr=self._param_attr, shape=[norm_size], dtype=self._dtype,
                default_initializer=Constant(1.0))
        if self._shift:
            self.bias = self.create_parameter(
                attr=self._bias_attr, shape=[norm_size], dtype=self._dtype,
                is_bias=True)

    def forward(self, input):
        norm_size = 1
        for d in input.shape[self._begin_norm_axis:]:
            norm_size *= int(d)
        if (self._scale and self.weight is None) or (
                self._shift and self.bias is None):
            self._build(norm_size)
        h = self._helper
        inputs = {"X": [input]}
        if self.weight is not None:
            inputs["Scale"] = [self.weight]
        if self.bias is not None:
            inputs["Bias"] = [self.bias]
        out = h.create_variable_for_type_inference(self._dtype)
        mean = h.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        var = h.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        h.append_op(
            type="layer_norm", inputs=inputs,
            outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
            attrs={"epsilon": self._epsilon,
                   "begin_norm_axis": self._begin_norm_axis})
        return h.append_activation(out, self._act)


class GroupNorm(Layer):
    def __init__(self, name_scope, channels, groups, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.weight = None if param_attr is False else self.create_parameter(
            attr=param_attr, shape=[channels], dtype=dtype,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            attr=bias_attr, shape=[channels], dtype=dtype, is_bias=True)

    def forward(self, input):
        h = self._helper
        inputs = {"X": [input]}
        if self.weight is not None:
            inputs["Scale"] = [self.weight]
        if self.bias is not None:
            inputs["Bias"] = [self.bias]
        out = h.create_variable_for_type_inference(self._dtype)
        mean = h.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        var = h.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        h.append_op(
            type="group_norm", inputs=inputs,
            outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
            attrs={"epsilon": self._epsilon, "groups": self._groups,
                   "data_layout": "NCHW"})
        return h.append_activation(out, self._act)


class PRelu(Layer):
    def __init__(self, name_scope, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [int(channel)]
        elif mode == "element":
            shape = [int(d) for d in input_shape[1:]]
        else:
            raise ValueError("mode must be all|channel|element")
        self.weight = self.create_parameter(
            attr=param_attr, shape=shape, dtype=dtype,
            default_initializer=Constant(0.25))

    def forward(self, input):
        h = self._helper
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(
            type="prelu", inputs={"X": [input], "Alpha": [self.weight]},
            outputs={"Out": [out]}, attrs={"mode": self._mode})
        return out


class Dropout(Layer):
    """Convenience stateful dropout honoring train()/eval()."""

    def __init__(self, p=0.5, seed=None):
        super().__init__("dropout")
        self._p = p
        self._seed = seed

    def forward(self, input):
        from .. import layers

        return layers.dropout(input, self._p,
                              is_test=not self.training, seed=self._seed,
                              dropout_implementation="upscale_in_train")


def np_prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _triple(v):
    return [v, v, v] if isinstance(v, int) else list(v)


class Conv3D(Layer):
    """3-D convolution (reference dygraph/nn.py Conv3D:270)."""

    def __init__(self, name_scope, num_channels, num_filters, filter_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, use_cudnn=True,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        groups = groups or 1
        fs = _triple(filter_size)
        self._attrs = {"strides": _triple(stride),
                       "paddings": _triple(padding),
                       "dilations": _triple(dilation), "groups": groups,
                       "data_format": "NCDHW"}
        self._act = act
        import math

        fan_in = (num_channels // groups) * fs[0] * fs[1] * fs[2]
        self.weight = self.create_parameter(
            attr=param_attr,
            shape=[num_filters, num_channels // groups] + fs, dtype=dtype,
            default_initializer=Normal(0.0, math.sqrt(2.0 / fan_in)))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[num_filters], dtype=dtype,
                is_bias=True)

    def forward(self, input):
        h = self._helper
        pre = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="conv3d",
                    inputs={"Input": [input], "Filter": [self.weight]},
                    outputs={"Output": [pre]}, attrs=dict(self._attrs))
        if self.bias is not None:
            out = h.create_variable_for_type_inference(self._dtype)
            h.append_op(type="elementwise_add",
                        inputs={"X": [pre], "Y": [self.bias]},
                        outputs={"Out": [out]}, attrs={"axis": 1})
            pre = out
        return h.append_activation(pre, self._act)


class Conv3DTranspose(Layer):
    """3-D transposed convolution (reference dygraph/nn.py:491)."""

    def __init__(self, name_scope, num_channels, num_filters, filter_size,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=1, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        groups = groups or 1
        fs = _triple(filter_size)
        self._attrs = {"strides": _triple(stride),
                       "paddings": _triple(padding),
                       "dilations": _triple(dilation), "groups": groups,
                       "data_format": "NCDHW",
                       "output_size": list(output_size) if output_size
                       else []}
        self._act = act
        self.weight = self.create_parameter(
            attr=param_attr,
            shape=[num_channels, num_filters // groups] + fs, dtype=dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[num_filters], dtype=dtype,
                is_bias=True)

    def forward(self, input):
        h = self._helper
        pre = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="conv3d_transpose",
                    inputs={"Input": [input], "Filter": [self.weight]},
                    outputs={"Output": [pre]}, attrs=dict(self._attrs))
        if self.bias is not None:
            out = h.create_variable_for_type_inference(self._dtype)
            h.append_op(type="elementwise_add",
                        inputs={"X": [pre], "Y": [self.bias]},
                        outputs={"Out": [out]}, attrs={"axis": 1})
            pre = out
        return h.append_activation(pre, self._act)


class GRUUnit(Layer):
    """Single GRU step (reference dygraph/nn.py GRUUnit:1653): input is
    the projected [B, 3D] gates, hidden [B, D]."""

    def __init__(self, name_scope, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size  # 3 * D, reference convention
        D = size // 3
        self._attrs = {
            "activation": {"identity": 0, "sigmoid": 1, "tanh": 2,
                           "relu": 3}[activation],
            "gate_activation": {"identity": 0, "sigmoid": 1, "tanh": 2,
                                "relu": 3}[gate_activation],
            "origin_mode": origin_mode,
        }
        self.weight = self.create_parameter(
            attr=param_attr, shape=[D, 3 * D], dtype=dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[1, 3 * D], dtype=dtype,
                is_bias=True)

    def forward(self, input, hidden):
        h = self._helper
        gate = h.create_variable_for_type_inference(self._dtype)
        reset = h.create_variable_for_type_inference(self._dtype)
        out = h.create_variable_for_type_inference(self._dtype)
        ins = {"Input": [input], "HiddenPrev": [hidden],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        h.append_op(type="gru_unit", inputs=ins,
                    outputs={"Gate": [gate], "ResetHiddenPrev": [reset],
                             "Hidden": [out]}, attrs=dict(self._attrs))
        return out, reset, gate


class NCE(Layer):
    """Noise-contrastive estimation loss (reference dygraph/nn.py
    NCE:1837)."""

    def __init__(self, name_scope, num_total_classes, dim,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=10, sampler="uniform",
                 custom_dist=None, seed=0, is_sparse=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {
            "num_total_classes": int(num_total_classes),
            "num_neg_samples": int(num_neg_samples),
            "seed": int(seed),
            "sampler": {"uniform": 0, "log_uniform": 1,
                        "custom_dist": 2}[sampler],
            "is_sparse": is_sparse,
        }
        if sampler == "custom_dist" and custom_dist is None:
            raise ValueError(
                "sampler='custom_dist' requires the custom_dist "
                "probability vector")
        self._custom_dist = custom_dist
        self._sample_weight = sample_weight
        self.weight = self.create_parameter(
            attr=param_attr, shape=[num_total_classes, dim], dtype=dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[num_total_classes], dtype=dtype,
                is_bias=True)

    def forward(self, input, label, sample_weight=None):
        from .base import to_variable

        h = self._helper
        cost = h.create_variable_for_type_inference(self._dtype)
        slog = h.create_variable_for_type_inference(self._dtype)
        slab = h.create_variable_for_type_inference("int64")
        ins = {"Input": [input], "Label": [label],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        if self._custom_dist is not None:
            import numpy as _np

            ins["CustomDistProbs"] = [to_variable(
                _np.asarray(self._custom_dist, "float32"))]
        sw = sample_weight if sample_weight is not None \
            else self._sample_weight
        if sw is not None:
            if not hasattr(sw, "numpy"):
                import numpy as _np

                sw = to_variable(_np.asarray(sw, "float32"))
            ins["SampleWeight"] = [sw]
        h.append_op(type="nce", inputs=ins,
                    outputs={"Cost": [cost], "SampleLogits": [slog],
                             "SampleLabels": [slab]},
                    attrs=dict(self._attrs))
        return cost


class BilinearTensorProduct(Layer):
    """out[:, k] = x W_k y^T (reference dygraph/nn.py:2178)."""

    def __init__(self, name_scope, size, x_dim, y_dim, name=None,
                 act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter(
            attr=param_attr, shape=[size, x_dim, y_dim], dtype=dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                attr=bias_attr, shape=[1, size], dtype=dtype, is_bias=True)

    def forward(self, x, y):
        h = self._helper
        out = h.create_variable_for_type_inference(self._dtype)
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        h.append_op(type="bilinear_tensor_product", inputs=ins,
                    outputs={"Out": [out]})
        return h.append_activation(out, self._act)


class SequenceConv(Layer):
    """Sequence convolution over [B, T, D] (reference dygraph/nn.py
    SequenceConv:2554; LoD ragged batching becomes the padded design)."""

    def __init__(self, name_scope, num_filters, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, input):
        h = self._helper
        if self.weight is None:
            D = int(input.shape[-1])
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[self._filter_size * D, self._num_filters],
                dtype=self._dtype)
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr, shape=[self._num_filters],
                    dtype=self._dtype, is_bias=True)
        pre = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="sequence_conv",
                    inputs={"X": [input], "Filter": [self.weight]},
                    outputs={"Out": [pre]},
                    attrs={"contextLength": self._filter_size,
                           "contextStart": -(self._filter_size // 2),
                           "contextStride": 1})
        if self.bias is not None:
            out = h.create_variable_for_type_inference(self._dtype)
            h.append_op(type="elementwise_add",
                        inputs={"X": [pre], "Y": [self.bias]},
                        outputs={"Out": [out]}, attrs={"axis": -1})
            pre = out
        return h.append_activation(pre, self._act)


class RowConv(Layer):
    """Lookahead row convolution (reference dygraph/nn.py RowConv:2648)."""

    def __init__(self, name_scope, future_context_size, param_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self._future = future_context_size
        self._param_attr = param_attr
        self.weight = None

    def forward(self, input):
        h = self._helper
        if self.weight is None:
            D = int(input.shape[-1])
            self.weight = self.create_parameter(
                attr=self._param_attr, shape=[self._future + 1, D],
                dtype=self._dtype)
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="row_conv",
                    inputs={"X": [input], "Filter": [self.weight]},
                    outputs={"Out": [out]})
        return h.append_activation(out, self._act)


class SpectralNorm(Layer):
    """Spectral weight normalization (reference dygraph/nn.py
    SpectralNorm:2827): persistent u/v power-iteration state."""

    def __init__(self, name_scope, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}
        self._u = None
        self._v = None

    def forward(self, weight):
        h = self._helper
        if self._u is None:
            import numpy as _np

            shape = [int(d) for d in weight.shape]
            dim = self._attrs["dim"]
            hh = shape[dim]
            ww = 1
            for i, d in enumerate(shape):
                if i != dim:
                    ww *= d
            self._u = self.create_parameter(
                attr=None, shape=[hh], dtype=self._dtype,
                default_initializer=Normal(0.0, 1.0))
            self._u.stop_gradient = True
            self._v = self.create_parameter(
                attr=None, shape=[ww], dtype=self._dtype,
                default_initializer=Normal(0.0, 1.0))
            self._v.stop_gradient = True
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="spectral_norm",
                    inputs={"Weight": [weight], "U": [self._u],
                            "V": [self._v]},
                    outputs={"Out": [out]}, attrs=dict(self._attrs))
        return out


class TreeConv(Layer):
    """Tree-based convolution (reference dygraph/nn.py TreeConv:2927).
    The tree_conv op emits the raw pre-activation conv; bias and the
    activation (default tanh) are applied here, matching the reference
    layer semantics."""

    def __init__(self, name_scope, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"max_depth": max_depth}
        self._act = act
        self._output_size = output_size
        self._num_filters = num_filters
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, nodes_vector, edge_set):
        h = self._helper
        if self.weight is None:
            F = int(nodes_vector.shape[-1])
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[F, 3, self._output_size, self._num_filters],
                dtype=self._dtype)
            if self._bias_attr is not False:
                # the op emits [B, N, output_size*num_filters] (flattened
                # feature dim in the padded design): bias matches it
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._output_size * self._num_filters],
                    dtype=self._dtype, is_bias=True)
        out = h.create_variable_for_type_inference(self._dtype)
        h.append_op(type="tree_conv",
                    inputs={"NodesVector": [nodes_vector],
                            "EdgeSet": [edge_set],
                            "Filter": [self.weight]},
                    outputs={"Out": [out]}, attrs=dict(self._attrs))
        if self.bias is not None:
            pre = h.create_variable_for_type_inference(self._dtype)
            h.append_op(type="elementwise_add",
                        inputs={"X": [out], "Y": [self.bias]},
                        outputs={"Out": [pre]}, attrs={"axis": -1})
            out = pre
        return h.append_activation(out, self._act)


__all__ += ["Conv3D", "Conv3DTranspose", "GRUUnit", "NCE",
            "BilinearTensorProduct", "SequenceConv", "RowConv",
            "SpectralNorm", "TreeConv"]
