"""Sequential container (reference dygraph/container.py:20)."""

from .layers import Layer

__all__ = ["Sequential"]


class Sequential(Layer):
    """Runs sub-layers in registration order.  Accepts either iterable
    Layers or (name, Layer) pairs; supports item access/assignment/deletion
    by index-or-name like the reference."""

    def __init__(self, name_scope, *layers):
        super().__init__(name_scope)
        if len(layers) > 0 and isinstance(layers[0], tuple):
            for name, layer in layers:
                self.add_sublayer(name, layer)
        else:
            for idx, layer in enumerate(layers):
                self.add_sublayer(str(idx), layer)

    def __getitem__(self, name):
        return self._sub_layers[str(name)]

    def __setitem__(self, name, layer):
        assert isinstance(layer, Layer)
        setattr(self, str(name), layer)

    def __delitem__(self, name):
        name = str(name)
        assert name in self._sub_layers
        del self._sub_layers[name]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input
