"""TracedLayer: trace a dygraph Layer into a static Program
(reference python/paddle/fluid/dygraph/jit.py:82 + the C++
ProgramDescTracer, paddle/fluid/imperative/jit/program_desc_tracer.cc).

Because dygraph layers emit the SAME ops as the static builders, tracing is
re-running ``forward`` with dygraph mode switched off under a fresh
program_guard; parameters are mirrored into the new program's global block
and their current values copied into a private Scope.  The result executes
through the normal block-compiling Executor (whole-program XLA compilation —
this is how a dygraph model gets the fused/compiled TPU fast path).
"""

import numpy as np

from .. import framework
from ..core.executor import Executor, scope_guard
from ..core.scope import Scope

__all__ = ["TracedLayer"]


def _persistable_vars_of(layer):
    """All Parameters + persistable state vars (e.g. BatchNorm running
    stats) owned by `layer` and its sublayers."""
    seen = {}
    for p in layer.parameters():
        seen[p.name] = p
    for l in [layer] + layer.sublayers():
        for v in l.__dict__.values():
            if isinstance(v, framework.Variable) and v.persistable:
                seen.setdefault(v.name, v)
    return list(seen.values())


class TracedLayer:
    def __init__(self, program, feed_vars, fetch_vars, scope, place=None):
        self.program = program
        self._feed_names = [v.name for v in feed_vars]
        self._fetch_vars = fetch_vars
        self._scope = scope
        self._place = place or framework.CPUPlace()
        self._exe = Executor(self._place)

    @staticmethod
    def trace(layer, inputs):
        """Returns (outputs_in_dygraph, traced_layer)."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        # run once eagerly for the dygraph-side outputs
        dy_out = layer(*inputs)

        state = _persistable_vars_of(layer)
        tracer = framework._dygraph_tracer_
        framework._dygraph_tracer_ = None
        try:
            main, startup = framework.Program(), framework.Program()
            with framework.program_guard(main, startup):
                gblock = main.global_block()
                for v in state:
                    gblock.create_var(
                        name=v.name, shape=v.shape, dtype=v.dtype,
                        persistable=True)
                feed_vars = []
                for i, x in enumerate(inputs):
                    arr = np.asarray(x.numpy())
                    feed_vars.append(gblock.create_var(
                        name="traced_in_%d" % i, shape=arr.shape,
                        dtype=arr.dtype, is_data=True, stop_gradient=True))
                out = layer.forward(*feed_vars)
            fetch_vars = list(out) if isinstance(out, (list, tuple)) else [out]
        finally:
            framework._dygraph_tracer_ = tracer

        scope = Scope()
        for v in state:
            scope.var(v.name).set(np.asarray(v._ivar))
        traced = TracedLayer(main, feed_vars, fetch_vars, scope)
        return dy_out, traced

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        feed = {}
        for name, x in zip(self._feed_names, inputs):
            feed[name] = x.numpy() if isinstance(x, framework.Variable) else np.asarray(x)
        with scope_guard(self._scope):
            return self._exe.run(self.program, feed=feed,
                                 fetch_list=self._fetch_vars)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from .. import io

        fetch_vars = self._fetch_vars
        if fetch is not None:
            fetch_vars = [fetch_vars[i] for i in fetch]
        feed_names = self._feed_names
        if feed is not None:
            feed_names = [feed_names[i] for i in feed]
        with scope_guard(self._scope):
            io.save_inference_model(dirname, feed_names, fetch_vars,
                                    self._exe, main_program=self.program)
