"""fleet parameter-server backend (parity:
python/paddle/fluid/incubate/fleet/parameter_server/distribute_transpiler/
__init__.py:407 DistributedTranspiler(Fleet)) over the native PS runtime
(distributed/ps.py + the C++ tensor RPC transport).

Usage mirrors the reference:

    fleet.init(role_maker)
    opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1), config)
    opt.minimize(loss)
    if fleet.is_server():
        fleet.init_server(); fleet.run_server()      # blocks in the loop
    else:
        fleet.init_worker()
        exe.run(fleet.main_program, ...)             # grads sync'd per step
        fleet.stop_worker()
"""

from ....framework import default_main_program, default_startup_program
from ....transpiler import DistributeTranspiler, DistributeTranspilerConfig
from ..base.fleet_base import DistributedOptimizer, Fleet, Mode

__all__ = ["fleet", "DistributedTranspiler", "TranspilerOptimizer"]


class DistributedTranspilerFleet(Fleet):
    def __init__(self):
        super().__init__(Mode.TRANSPILER)
        self._transpiler = None
        self.main_program = None
        self.startup_program = None
        self._server_program = None
        self._server_startup = None

    # -- worker side ---------------------------------------------------------
    def init_worker(self):
        pass  # comms are created lazily on the first exe.run (executor.py)

    def run_worker(self, main_programs=None, scopes=None):
        pass

    def stop_worker(self):
        """Send COMPLETE to every pserver (reference: fleet.stop_worker ->
        Communicator stop + SendComplete)."""
        self._executor.close()
        from ....core.executor import global_scope

        comm = getattr(global_scope(), "_ps_comm", None)
        if comm is not None:
            comm.complete()

    # -- server side ---------------------------------------------------------
    def init_server(self, model_dir=None):
        ep = self.server_endpoints[self.server_index()]
        self._server_program, self._server_startup = \
            self._transpiler.get_pserver_programs(ep)
        self._executor.run(self._server_startup)
        if model_dir:
            from .... import io

            io.load_persistables(self._executor, model_dir,
                                 self._server_program)

    def run_server(self):
        if self._server_program is None:
            raise RuntimeError("call init_server() before run_server()")
        self._executor.run(self._server_program)  # blocks in the PS loop

    # -- optimizer -----------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = TranspilerOptimizer(optimizer, strategy, self)
        return self._optimizer

    def _transpile(self, config):
        t = DistributeTranspiler(config=config)
        t.transpile(
            trainer_id=self.worker_index(),
            pservers=",".join(self.server_endpoints),
            trainers=self.worker_num(),
            sync_mode=getattr(config, "sync_mode", True))
        self._transpiler = t
        if self.is_worker():
            self.main_program = t.get_trainer_program()
            self.startup_program = default_startup_program()
        else:
            self.main_program = default_main_program()
            self.startup_program = default_startup_program()

    # -- save ----------------------------------------------------------------
    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None,
                             export_for_deployment=True):
        from .... import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or self.main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        return io.save_persistables(executor, dirname,
                                    main_program or self.main_program)


class TranspilerOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_obj=None):
        super().__init__(optimizer, strategy)
        if strategy is None:
            strategy = DistributeTranspilerConfig()
        elif not isinstance(strategy, DistributeTranspilerConfig):
            raise TypeError(
                "strategy must be a DistributeTranspilerConfig")
        self._strategy = strategy
        self._fleet = fleet_obj

    def backward(self, *a, **k):
        return self._optimizer.backward(*a, **k)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, losses, scopes=None, startup_programs=None,
                 parameter_list=None, no_grad_set=None):
        out = self._optimizer.minimize(
            losses, startup_programs, parameter_list, no_grad_set)
        self._fleet._transpile(self._strategy)
        return out


fleet = DistributedTranspilerFleet()
DistributedTranspiler = DistributedTranspilerFleet
