"""Alias module matching the reference import path
(incubate/fleet/parameter_server/distribute_transpiler/__init__.py)."""

from . import fleet, DistributedTranspiler, TranspilerOptimizer  # noqa: F401
