"""Fleet base classes (port of incubate/fleet/base/fleet_base.py:345)."""

import abc

from ....core.executor import Executor

__all__ = ["Fleet", "DistributedOptimizer", "Mode"]


class Mode:
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3


class Fleet(metaclass=abc.ABCMeta):
    def __init__(self, mode):
        self._is_initialized = False
        self._mode = mode
        self._optimizer = None
        self._role_maker = None
        self._executor = None

    def init(self, role_maker=None):
        from . import role_maker as rm

        if role_maker is None:
            role_maker = rm.UserDefinedCollectiveRoleMaker()
        self._role_maker = role_maker
        self._role_maker.generate_role()
        self._executor = Executor()
        self._is_initialized = True

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def is_server(self):
        return self._role_maker.is_server()

    @property
    def worker_endpoints(self):
        return self._role_maker.get_trainer_endpoints()

    @property
    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    # subclass API ----------------------------------------------------------
    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        pass

    @abc.abstractmethod
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        pass

    @abc.abstractmethod
    def save_persistables(self, executor, dirname, main_program=None):
        pass


class DistributedOptimizer(metaclass=abc.ABCMeta):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def minimize(self, losses, scopes=None, startup_programs=None,
                 parameter_list=None, no_grad_set=None):
        pass

    def __getattr__(self, item):
        return getattr(self._optimizer, item)
