"""Role makers: who am I in the job? (port of
python/paddle/fluid/incubate/fleet/base/role_maker.py:327).

PaddleCloudRoleMaker reads the same env-var scheme as the reference
(PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / PADDLE_PSERVERS_IP_PORT_LIST
/ TRAINING_ROLE), which paddle_tpu.distributed.launch sets.  On TPU a
"trainer" is a host process driving its local chips; multi-host jobs
bootstrap jax.distributed from the same env vars.
"""

import os

__all__ = [
    "Role",
    "RoleMakerBase",
    "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker",
]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False
        self._role = None
        self._current_id = -1

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self._role == Role.WORKER and self._current_id == 0

    def worker_num(self):
        return len(self._worker_endpoints) or 1

    def server_num(self):
        return len(self._server_endpoints)

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._role_is_generated:
            return
        if self._is_collective:
            self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170")
            self._worker_endpoints = eps.split(",")
            self._role = Role.WORKER
        else:
            role = os.getenv("TRAINING_ROLE", "TRAINER")
            eps = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = eps.split(",") if eps else []
            worker_eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = worker_eps.split(",") if worker_eps else []
            if role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
            else:
                self._role = Role.SERVER
                cur = os.getenv("POD_IP", "127.0.0.1") + ":" + os.getenv(
                    "PADDLE_PORT", "6174")
                self._current_id = (
                    self._server_endpoints.index(cur)
                    if cur in self._server_endpoints else 0
                )
        self._role_is_generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = ["127.0.0.1:%d" % (6170 + i)
                                  for i in range(worker_num)]

    def worker_num(self):
        return self._worker_num

    def generate_role(self):
        self._role_is_generated = True


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:6170"]
        self._role = Role.WORKER

    def generate_role(self):
        self._role_is_generated = True
