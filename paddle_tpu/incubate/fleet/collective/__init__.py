"""fleet collective backend (port of incubate/fleet/collective/__init__.py:
Collective(Fleet) at :45, CollectiveOptimizer at :182, DistributedStrategy
at :134).

`fleet.distributed_optimizer(opt).minimize(loss)` applies the GradAllReduce
transpiler so the main program carries scale + c_allreduce_sum per grad; the
executor then runs it SPMD over the local chip mesh (shard_map + lax.psum),
which is the TPU equivalent of the reference's one-process-per-GPU NCCL
rings.  Multi-host scaling bootstraps jax.distributed from the same env-var
scheme the reference's launcher sets.
"""

from ....compiler import BuildStrategy
from ....framework import default_main_program, default_startup_program
from ..base.fleet_base import DistributedOptimizer, Fleet, Mode

__all__ = ["fleet", "Collective", "CollectiveOptimizer", "DistributedStrategy"]


class DistributedStrategy:
    """Strategy knobs (reference collective/__init__.py:134)."""

    def __init__(self):
        self.use_local_sgd = False
        self.local_sgd_frequency = 1
        self.mode = "grad_allreduce"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15
        self.exec_strategy = None
        self.build_strategy = BuildStrategy()


class Collective(Fleet):
    def __init__(self):
        super().__init__(Mode.COLLECTIVE)
        self._local_ip = 0
        self.startup_program = None
        self.main_program = None

    def init_worker(self):
        pass

    def run_worker(self, main_programs=None, scopes=None):
        pass

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer

    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None,
                             export_for_deployment=True):
        from .... import io

        return io.save_inference_model(dirname, feeded_var_names,
                                       target_vars, executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None,
                          filename=None):
        from .... import io

        return io.save_persistables(executor, dirname, main_program, filename)


fleet = Collective()


class CollectiveOptimizer(DistributedOptimizer):
    """Wraps an optimizer; minimize applies the collective transpiler
    (reference collective/__init__.py:182)."""

    def __init__(self, optimizer, strategy=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self.print_config = False

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def _get_node_ips_from_endpoints(self, endpoints):
        return list(dict.fromkeys(ep.split(":")[0] for ep in endpoints))

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        strategy = self._strategy
        optimizer = self._optimizer
        if strategy.use_amp:
            from ....contrib import mixed_precision

            optimizer = mixed_precision.decorate(
                optimizer, init_loss_scaling=strategy.amp_loss_scaling,
                use_dynamic_loss_scaling=True)
        if strategy.forward_recompute:
            from ....optimizer import RecomputeOptimizer

            optimizer = RecomputeOptimizer(optimizer)
            optimizer._set_checkpoints(strategy.recompute_checkpoints)

        main_program = loss.block.program
        startup_program = startup_program or default_startup_program()

        optimize_ops, params_grads = optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        worker_endpoints = fleet.worker_endpoints or ["127.0.0.1:6170"]
        trainer_id = fleet.worker_index()
        current_endpoint = (
            worker_endpoints[trainer_id]
            if trainer_id < len(worker_endpoints) else worker_endpoints[0]
        )

        from ....transpiler.collective import (LocalSGD,
                                               select_grad_transpiler)

        # nranks for gradient scaling: number of SPMD ranks = local devices
        # per host x hosts (each rank sees 1/nranks of the global batch)
        import jax

        n_dev = len(jax.devices())
        nranks = max(n_dev, len(worker_endpoints))
        if len(worker_endpoints) > n_dev:
            import warnings

            warnings.warn(
                "fleet: %d worker endpoints but only %d visible devices — "
                "multi-host jobs must call "
                "paddle_tpu.distributed.launch.init_multihost() before "
                "building the model so jax.distributed exposes all chips"
                % (len(worker_endpoints), n_dev))
        if nranks > 1:
            if strategy.use_local_sgd:
                t = LocalSGD(strategy.nccl_comm_num)
            else:
                # FLAGS_collective_mode: replicated GradAllReduce vs
                # ZeRO-1 ShardedGradAllReduce (weight-update sharding)
                t = select_grad_transpiler(strategy.nccl_comm_num)
            eps = worker_endpoints
            if len(eps) < nranks:
                eps = ["local:%d" % i for i in range(nranks)]
                current = eps[trainer_id] if trainer_id < nranks else eps[0]
            else:
                current = current_endpoint
            t.transpile(startup_program, main_program, trainer_id, eps,
                        current)

        fleet.main_program = main_program
        fleet.startup_program = startup_program
        return optimize_ops, params_grads
