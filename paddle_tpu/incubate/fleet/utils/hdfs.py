"""fleet.utils.hdfs compatibility module (reference
python/paddle/fluid/incubate/fleet/utils/hdfs.py)."""

from ....utils.fs import HDFSClient  # noqa: F401

__all__ = ["HDFSClient"]
