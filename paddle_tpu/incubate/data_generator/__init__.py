"""User-side authoring API for the Dataset multislot text format.

Parity: python/paddle/fluid/incubate/data_generator/__init__.py.  A user
subclasses DataGenerator, overrides ``generate_sample`` (and optionally
``generate_batch``), then runs the script as a pipe filter: each input line
becomes one or more output records of the MultiSlotDataFeed text format
``<ids_num> id1 id2 ... <ids_num> ...`` — the same format our native
``multislot.cc`` parser and the Dataset/trainer path consume."""

import sys

__all__ = ["MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Base class: drives generate_sample/generate_batch over stdin or an
    in-memory source and serializes records with the subclass ``_gen_str``."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError("line_limit%s must be in int type"
                             % type(line_limit))
        if line_limit < 1:
            raise ValueError("line_limit can not less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        """Batch size seen by generate_batch (only relevant if overridden)."""
        self.batch_size_ = batch_size

    def _flush(self, batch_samples, write):
        batch_iter = self.generate_batch(batch_samples)
        for sample in batch_iter():
            write(self._gen_str(sample))

    def _run(self, lines, write):
        batch_samples = []
        for line in lines:
            line_iter = self.generate_sample(line)
            for parsed in line_iter():
                if parsed is None:
                    continue
                batch_samples.append(parsed)
                if len(batch_samples) == self.batch_size_:
                    self._flush(batch_samples, write)
                    batch_samples = []
        if batch_samples:
            self._flush(batch_samples, write)

    def run_from_memory(self):
        """Generate from memory (generate_sample is called with line=None);
        for debugging and benchmarks."""
        self._run([None], sys.stdout.write)

    def run_from_stdin(self):
        """Pipe-filter mode: stdin lines -> multislot records on stdout."""
        self._run(sys.stdin, sys.stdout.write)

    # -- test/TPU-pipeline convenience (not in the reference API) ------------
    def run_to_file(self, lines, path):
        """Run the generator over an iterable of lines into a file — the
        same serialization as run_from_stdin without process plumbing, so a
        Dataset can point at the result directly."""
        with open(path, "w") as f:
            self._run(lines, f.write)

    def _gen_str(self, line):
        raise NotImplementedError(
            "pls use MultiSlotDataGenerator or PairWiseDataGenerator")

    def generate_sample(self, line):
        """Override: return a no-arg generator yielding
        ``[(slot_name, [feasign, ...]), ...]`` per record."""
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: "
            "[(name, [feasign, ...]), ...] or ((name, [feasign, ...]), ...)")

    def generate_batch(self, samples):
        """Override for batch-level preprocessing (e.g. padding); default
        passes samples through one by one."""

        def local_iter():
            for sample in samples:
                yield sample

        return local_iter


def _check_slot(item):
    name, elements = item
    if not isinstance(name, str):
        raise ValueError("name%s must be in str type" % type(name))
    if not isinstance(elements, list):
        raise ValueError("elements%s must be in list type" % type(elements))
    if not elements:
        raise ValueError(
            "the elements of each field can not be empty, you need padding "
            "it in process().")
    return name, elements


class MultiSlotStringDataGenerator(DataGenerator):
    """Serializes ``[(name, [str, ...]), ...]`` records; values are emitted
    verbatim (fastest path — no type bookkeeping)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type"
                "Examples: [('words', ['1926', '08', '17']), "
                "('label', ['1'])]")
        out = []
        for name, elements in line:
            out.append(str(len(elements)))
            out.extend(elements)
        return " ".join(out) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Serializes ``[(name, [int|float, ...]), ...]`` records, tracking the
    per-slot dtype in ``_proto_info`` (a slot becomes "float" as soon as any
    float appears) and validating slot-set consistency across records."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type"
                "Example: [('words', [1926, 08, 17]), ('label', [1])]")
        first = self._proto_info is None
        if first:
            self._proto_info = [(_check_slot(item)[0], "uint64")
                                for item in line]
        elif len(line) != len(self._proto_info):
            raise ValueError(
                "the complete field set of two given line are inconsistent.")
        out = []
        for index, item in enumerate(line):
            name, elements = _check_slot(item)
            if name != self._proto_info[index][0]:
                raise ValueError(
                    "the field name of two given line are not match: "
                    "require<%s>, get<%s>."
                    % (self._proto_info[index][0], name))
            out.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, float):
                    self._proto_info[index] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        "the type of element%s must be in int or float"
                        % type(elem))
                out.append(str(elem))
        return " ".join(out) + "\n"
