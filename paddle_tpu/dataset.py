"""fluid.dataset analog: file-backed datasets parsed by the native C++ store.

Parity: python/paddle/fluid/dataset.py (DatasetFactory:819, InMemoryDataset,
QueueDataset) over the C++ MultiSlot data feed
(paddle/fluid/framework/data_feed.h:532, data_set.h:135).  Files are
MultiSlot text: per line, for each declared slot, ``<n> <v1> ... <vn>``.
Parsing/shuffling runs in C++ (paddle_tpu/native/csrc/multislot.cc); batches
come back as dense padded arrays (ragged slots pad to the batch max — the
LoD→mask design, SURVEY §5 long-context note).
"""

import ctypes

import numpy as np

from .framework import Variable, dtype_to_np

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset",
           "DatasetLoader"]


class DatasetFactory:
    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread = 1
        self._filelist = []
        self._use_vars = []
        self._pipe_command = "cat"
        self._rank = 0
        self._nranks = 1
        self._store = None
        self._hdfs_config = None

    # -- reference API surface ----------------------------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread = thread_num

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        for v in var_list:
            if not isinstance(v, Variable):
                raise TypeError("set_use_var expects Variables")
        self._use_vars = list(var_list)

    def set_pipe_command(self, pipe_command):
        self._pipe_command = pipe_command  # accepted; parsing is native

    def set_hdfs_config(self, fs_name, fs_ugi):
        self._hdfs_config = (fs_name, fs_ugi)

    def set_download_cmd(self, download_cmd):
        pass

    # -- native store --------------------------------------------------------
    def _slot_types(self):
        types = []
        for v in self._use_vars:
            dt = v.dtype or "float32"
            types.append(0 if dt.startswith("int") else 1)
        return types

    def _ensure_store(self):
        from .native import load

        if self._store is None:
            lib = load()
            types = (ctypes.c_int * len(self._use_vars))(*self._slot_types())
            self._store = lib.ms_create(len(self._use_vars), types)
            self._lib = lib
        return self._store

    def _load_files(self, files):
        store = self._ensure_store()
        total = 0
        for path in files:
            n = self._lib.ms_load_file(store, path.encode())
            if n < 0:
                raise IOError("cannot read dataset file %r" % path)
            total += n
        return total

    def _num_records(self):
        if self._store is None:
            return 0
        return self._lib.ms_num_records(self._store)

    def _batch(self, begin, end):
        """Extract records [begin, end) as a feed dict of padded arrays."""
        store = self._ensure_store()
        lib = self._lib
        n = end - begin
        feed = {}
        for slot, var in enumerate(self._use_vars):
            lengths = (ctypes.c_int64 * n)()
            total = lib.ms_batch_slot_len(store, begin, end, slot)
            is_int = self._slot_types()[slot] == 0
            buf = np.empty(int(total), dtype=np.int64 if is_int else np.float32)
            lib.ms_batch_fill(
                store, begin, end, slot,
                buf.ctypes.data_as(ctypes.c_void_p), lengths)
            lens = np.frombuffer(lengths, dtype=np.int64)
            maxlen = int(lens.max()) if n else 0
            if n and (lens == lens[0]).all():
                arr = buf.reshape(n, int(lens[0]))
            else:
                arr = np.zeros((n, maxlen), dtype=buf.dtype)
                off = 0
                for i, ln in enumerate(lens):
                    arr[i, : int(ln)] = buf[off:off + int(ln)]
                    off += int(ln)
            want = dtype_to_np(var.dtype or "float32")
            if arr.dtype != want:
                arr = arr.astype(want)
            feed[var.name] = arr
        return feed

    def _iter_batches(self, drop_last=True):
        n = self._num_records()
        bs = self._batch_size
        end = (n // bs) * bs if drop_last else n
        for begin in range(0, end, bs):
            yield self._batch(begin, min(begin + bs, n))

    def desc(self):
        return {
            "batch_size": self._batch_size,
            "thread": self._thread,
            "slots": [v.name for v in self._use_vars],
        }


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset: load_into_memory
    + local/global shuffle through the PS channel; here global shuffle
    re-seeds deterministically per rank over the same files)."""

    def __init__(self):
        super().__init__()
        self._loaded = False
        self._seed = 0

    def load_into_memory(self):
        files = self._filelist[self._rank::self._nranks] \
            if self._nranks > 1 else self._filelist
        self._load_files(files)
        self._loaded = True

    def local_shuffle(self):
        self._ensure_store()
        self._lib.ms_shuffle(self._store, self._seed)
        self._seed += 1

    def global_shuffle(self, fleet=None, thread_num=12):
        # all ranks shuffle with a shared seed; with per-rank file splits the
        # union over ranks is a global permutation of the corpus
        self._ensure_store()
        self._lib.ms_shuffle(self._store, 0x9E3779B9 + self._seed)
        self._seed += 1

    def release_memory(self):
        if self._store is not None:
            self._lib.ms_clear(self._store)
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return int(self._num_records())

    def get_shuffle_data_size(self, fleet=None):
        return int(self._num_records())


class QueueDataset(DatasetBase):
    """Streaming dataset: files parsed lazily epoch by epoch."""

    def _iter_batches(self, drop_last=True):
        # parse (native) then stream; store cleared after the epoch
        self._ensure_store()
        self._lib.ms_clear(self._store)
        self._load_files(self._filelist)
        yield from super()._iter_batches(drop_last)
        self._lib.ms_clear(self._store)


class DatasetLoader:
    """DataLoader.from_dataset: iterate a Dataset as feed dicts."""

    def __init__(self, dataset, places=None, drop_last=True):
        self._dataset = dataset
        self._drop_last = drop_last

    def __iter__(self):
        return self._dataset._iter_batches(self._drop_last)
