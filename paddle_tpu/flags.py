"""Runtime flags (analog of the reference's gflags surface,
paddle/fluid/platform/flags.cc + __bootstrap__ env reading in
python/paddle/fluid/__init__.py:132-220 + the pybind
global_value_getter_setter).

Flags whose semantics dissolve into XLA/PJRT (allocator strategy, GPU memory
fractions, eager deletion thresholds) are accepted as inert for API
compatibility; behavioral ones (check_nan_inf, benchmark) are honored by the
executor/dygraph paths.
"""

import os

__all__ = ["set_flags", "get_flags"]

_DEFAULTS = {
    # honored
    "FLAGS_check_nan_inf": False,       # flags.cc:44 — scan outputs for NaN/Inf
    # ghost-batch BN statistics: estimate batch stats from every k-th
    # sample (1 = exact reference semantics); read at layer-build time
    "FLAGS_bn_stat_subsample": 1,
    # capacity of tensor arrays carried through data-dependent while loops
    # (XLA needs a static bound; reference while_op grows arrays freely)
    "FLAGS_tensor_array_max_len": 256,
    # horizontal optimizer-update fusion (reference BuildStrategy
    # fuse_all_optimizer_ops / ir/fuse_optimizer_ops_pass.cc): coalesce
    # per-parameter sgd/momentum/adam ops into one flat update — ~46 ms
    # of a 211 ms ResNet-50 step was per-weight launch overhead
    "FLAGS_fuse_optimizer_ops": True,
    # per-request PS RPC deadline in MILLISECONDS (reference units —
    # paddle/fluid/operators/distributed/ FLAGS_rpc_deadline, default
    # 180000): a pserver that hangs mid-round raises ConnectionError on
    # the trainer instead of blocking its recv() forever.  <=0 disables.
    "FLAGS_rpc_deadline": 180000,
    # bounded reconnect-and-retry on RPC deadline/transport failures
    # (reference FLAGS_rpc_retry_times, grpc_client.cc): each retry opens
    # a FRESH connection (a timed-out socket may be mid-frame) after an
    # exponential backoff with jitter.  0 restores poison-on-first-failure.
    "FLAGS_rpc_retry_times": 3,
    # fault-injection spec "point:kind:prob[:count[:skip]];..." checked by
    # utils/fault_injection.maybe_fail at named runtime fault points
    # (rpc.send, rpc.get, ps.round, ckpt.write).  Empty = disarmed.
    "FLAGS_fault_spec": "",
    # pserver-side worker liveness timeout in SECONDS
    # (heart_beat_monitor.h): a trainer silent this long is EVICTED from
    # the sync quorum (rounds re-quorum on survivors) until it re-contacts.
    "FLAGS_worker_hb_timeout": 60.0,
    # layout-matched persistent params (core/lowering.py param carry): AMP
    # programs pin eligible weights in their bf16 compute dtype ACROSS steps
    # (the scope keeps the f32 master for the optimizer), so the compiled
    # step stops re-materializing f32->bf16 converts + layout copies of
    # ~85 MB of encoder weights every iteration.  Safe default-on: carry
    # engages only where it is bitwise-identical to the per-step cast
    # (single-consumer matmul/conv weights, single-process, no mesh).
    "FLAGS_layout_match_params": True,
    # unified runtime telemetry (core/telemetry.py): process-wide metrics
    # registry (counters/gauges/histograms) + JSONL step-event log.  Zero
    # cost when off (every mutator early-returns on this flag, the
    # profiler.is_profiler_enabled guard pattern).
    "FLAGS_telemetry": False,
    # where telemetry streams steps.jsonl and dump() writes metrics.json /
    # metrics.prom; empty = in-memory only (snapshot()/__metrics__ RPC
    # still work, nothing touches disk)
    "FLAGS_telemetry_dir": "",
    # size bound (bytes) for the append-only JSONL streams under
    # FLAGS_telemetry_dir (steps.jsonl + the tracing trace-<pid>.jsonl):
    # when a stream exceeds it, the file is rotated to <name>.1 (one
    # previous generation kept) so long fleet soaks stay disk-bounded.
    # <=0 disables rotation.
    "FLAGS_telemetry_max_bytes": 256 << 20,
    # distributed tracing (core/tracing.py): cross-process request/step
    # spans (trace_id/span_id/parent_id, W3C-style traceparent propagated
    # through the serving meta + RPC SEND frames) streamed as JSONL
    # (trace-<pid>.jsonl under FLAGS_telemetry_dir) and merged by
    # tools/trace_view.py into one Chrome/Perfetto trace.json.  Zero cost
    # when off: every span call early-returns on this one flag read, and
    # no trace file is ever created.
    "FLAGS_tracing": False,
    # static Program verifier (core/analysis.py): off | warn | error.
    # "warn" (default) runs the four rule families (well-formedness,
    # type/shape flow, donation/aliasing, distributed lint) on every
    # executor cache-miss compile and post-transpile, logging a
    # ProgramVerifyWarning + counting static_check_warnings into telemetry;
    # "error" raises one readable ProgramVerificationError report instead
    # of an opaque XLA traceback; "off" costs a single flag read
    "FLAGS_static_check": "warn",
    # HBM footprint auditor (core/memory_audit.py): after each compile, log
    # the executable's memory_analysis (arg/output/temp/alias bytes) with
    # per-variable attribution of the argument footprint.  Diagnostic; adds
    # one extra AOT compile per cache entry, so default-off.
    "FLAGS_hbm_audit": False,
    # per-replica HBM budget (bytes) for the static peak estimator
    # (core/world_analysis.py MEM003): when > 0, a predicted peak above
    # the budget becomes a MEM003 diagnostic pre-compile (error mode
    # raises) instead of an on-chip band-edge trip.  0 disables the gate;
    # MEM001 (the estimate itself) is always reported at info level.
    "FLAGS_hbm_budget_bytes": 0,
    # max param rank eligible for horizontal optimizer fusion
    # (ir.py FuseOptimizerOpsPass).  2 fuses BERT's [h,h]/[h,4h] encoder
    # weights into one fused_adam group (the r5 wgrad/Adam residue) while
    # keeping 4-D conv kernels unfused — flattening tiled TPU layouts
    # costs relayout copies exceeding the launch savings (round-3:
    # fuse-everything = 1786 img/s vs 2200 unfused).  0 = no restriction.
    "FLAGS_fuse_optimizer_max_rank": 2,
    # opt-in fused Pallas LayerNorm (pallas_kernels/layer_norm.py): wins
    # standalone microbenches, measured -1.5% inside full BERT on the
    # bench chip (breaks XLA's LN-neighbor fusions) — see ops/nn.py
    "FLAGS_use_pallas_layer_norm": False,
    # opt-in fused conv+bn+relu trunk block (pallas_kernels/conv_block.py):
    # one VMEM-resident pass per image over the NCHW ResNet trunk shapes
    # (inference folds the BN affine; training emits the batch statistics).
    # Adoption is probe-gated (pallas_kernels/adoption.py): even with the
    # flag on, the kernel engages only where shape/dtype checks pass AND a
    # tools/probes/ op_bench row shows >=1.1x over the XLA fallback.
    "FLAGS_use_pallas_conv_block": False,
    # opt-in fused optimizer-step kernel (pallas_kernels/fused_opt.py):
    # Adam/momentum moment recurrence + param AXPY + the bf16 param-carry
    # cast in ONE pass over the flat fused group (the PR-2 fuse_optimizer
    # grouping), so moments/params stream HBM once instead of three times.
    # Bitwise-identical to the unfused fused_adam expression; probe-gated.
    "FLAGS_use_pallas_fused_opt": False,
    # opt-in block-sparse embedding-bag gather/sum kernel
    # (pallas_kernels/embedding_bag.py): scalar-prefetched row indices
    # drive the DMA schedule, opening the recommender/sparse-table path
    # (distributed/sparse_table.py) at high QPS.  Probe-gated.
    "FLAGS_use_pallas_embedding_bag": False,
    # deterministic collective reduction order (ops/collective.py
    # c_allreduce_sum): replace lax.psum with all_gather + a fixed-order
    # pairwise tree-reduce, so the cross-rank gradient sum reassociates
    # identically regardless of ring schedule — the dp-sharded
    # reduction-reassociation item (ROADMAP; test_dp4_tp2 step-2 drift).
    # Costs gather bandwidth over psum, so default off.
    "FLAGS_deterministic_reduction": False,
    # small-seq fused training attention (in-kernel mask+dropout,
    # pallas_kernels/flash_attention.py small_attention_*): measured
    # 3.1x faster fwd in isolation but 18% SLOWER in-step at bs224
    # (889 vs 1081 seqs/s — the recompute backward's serial per-head
    # VPU chain loses to XLA's materialized-probs backward), so the
    # composed emission stays the default training path (BASELINE.md r5)
    "FLAGS_fused_small_attention": False,
    # two-tier persistent compilation cache (core/compile_cache.py).
    # Non-empty = enabled: <dir>/xla holds JAX's native persistent XLA
    # cache (jax_compilation_cache_dir, tier A — dedupes identical HLO
    # even across different programs); <dir>/aot holds framework-level
    # serialized executables keyed by (program content hash, trace-flag
    # fingerprint, collective world, feed shapes/dtypes) (tier B — a hit
    # skips trace + lower + compile entirely).  Empty = both tiers off.
    "FLAGS_compile_cache_dir": "",
    # tier-B size cap in bytes; least-recently-used entries are evicted
    # after each store once the total exceeds it.  <=0 disables eviction.
    "FLAGS_compile_cache_max_bytes": 1 << 30,
    # elastic standby worlds (distributed/elastic.py): after each epoch
    # adoption, a background thread pre-transpiles + pre-verifies views
    # for worlds N-1 and N-2 (every single-member loss, plus the
    # two-member loss) and pre-compiles them into the tier-B cache, so a
    # re-quorum becomes cache-restore + checkpoint-restore.  0 disables.
    "FLAGS_elastic_standby": 2,
    # collective gradient-exchange strategy (transpiler/collective.py):
    # "allreduce" = replicated GradAllReduce (every rank updates every
    # param); "zero1" = ShardedGradAllReduce, the ZeRO-1 weight-update
    # sharding pass (arXiv 2004.13336): reduce-scatter the gradients,
    # each rank runs the optimizer only on its 1/nranks param shard
    # (optimizer-state HBM drops by nranks), then all-gather the updated
    # params.  Params whose dim 0 does not divide the world, or whose
    # optimizer is not elementwise (lamb/lars need global norms), fall
    # back per-param to the replicated update.
    "FLAGS_collective_mode": "allreduce",
    # wire dtype for the gradient exchange (EQuARX, arXiv 2506.17615):
    # f32 = bitwise-parity escape hatch (plain psum / psum_scatter);
    # bf16 / int8 = bucketed per-tensor-scale quantization before the
    # wire, dequant after.  int8 cuts bytes-on-ICI per step to ~0.25x of
    # the f32 ring all-reduce (payload + per-bucket f32 scales).
    "FLAGS_allreduce_dtype": "f32",
    # quantization bucket (elements) for FLAGS_allreduce_dtype=int8:
    # one f32 max-abs scale per bucket per destination rank.  Smaller =
    # tighter scales (less quant error) but more scale bytes on the wire.
    "FLAGS_allreduce_quant_bucket": 512,
    # async snapshot-to-host checkpointing (io.CheckpointManager): save()
    # costs the step path ONE D2H host snapshot; serialization, crc32 and
    # the atomic _SUCCESS-sealed directory write run on a background
    # writer thread (at most one snapshot in flight — a save arriving
    # while one is writing is dropped LOUDLY via
    # checkpoint_save_overlap_total + a warning).  The telemetry split
    # checkpoint_save_stall_ms (foreground) vs checkpoint_write_ms
    # (background) proves the stall left the step path.
    "FLAGS_checkpoint_async": False,
    # shard-aware checkpoints under FLAGS_collective_mode=zero1: each
    # rank writes only its own dim-0 slice of the sharded optimizer
    # state (__shard_<r>of<w>__.npz; the _SUCCESS manifest records the
    # layout exported by the transpiler), rank 0 writes the replicated
    # vars once and seals.  restore() reassembles from whatever world
    # the checkpoint was written by, so world changes re-shard for free.
    # Off = every saver writes the full state (pre-sharding format,
    # still readable by restore).
    "FLAGS_checkpoint_sharded": True,
    # peer-to-peer elastic restore (distributed/elastic.py): on
    # re-quorum the adopted view prefers live post-step state held by
    # survivors — their own scope, or an RPC fetch over the control
    # fabric for a rejoining member — over re-reading the filesystem;
    # latest_valid() remains the fallback when no survivor has state
    # (checkpoint_restore_source_total{peer|fs}).  The COORDINATOR's
    # flag decides for the whole world (the chosen resume step rides
    # the published view), so members can never disagree on where to
    # resume.
    "FLAGS_checkpoint_p2p_restore": True,
    # elastic collective re-quorum (distributed/elastic.py): member
    # heartbeat period over the PADDLE_COORDINATOR control channel, and how
    # long a member may stay silent before the quorum evicts it and the
    # survivors re-form the world (seconds)
    "FLAGS_elastic_hb_interval": 0.5,
    "FLAGS_elastic_hb_timeout": 5.0,
    # control-channel port = member endpoint port + this offset (the member
    # endpoint port itself belongs to jax.distributed / the data plane)
    "FLAGS_elastic_ctrl_offset": 1000,
    # each quorum epoch moves the jax.distributed coordinator to
    # base_port + epoch * stride (the old world's sockets are parked, not
    # closed — see elastic.py on why tearing them down is fatal)
    "FLAGS_elastic_port_stride": 29,
    # continuous-batching inference serving (paddle_tpu/serving/):
    # shape buckets the batcher pads request batches to — every bucket is
    # AOT-compiled at startup (Executor.warmup against
    # FLAGS_compile_cache_dir) so no request ever pays an XLA compile
    "FLAGS_serving_buckets": "1,4,16,64",
    # admission-queue depth cap; beyond it requests are shed with a
    # retry-after instead of queued
    "FLAGS_serving_max_queue": 256,
    # default per-tenant deadline budget (ms): admission sheds a request
    # when projected queue wait already exceeds it
    "FLAGS_serving_deadline_ms": 2000.0,
    # how long the batcher waits to coalesce more same-model requests
    # toward the next larger bucket before dispatching (ms)
    "FLAGS_serving_batch_window_ms": 2.0,
    # serving-fleet replica heartbeat period / silence-eviction timeout
    # (seconds) — the serving analog of the elastic quorum knobs; the
    # fleet coordinator rewrites the endpoints file when a replica dies
    "FLAGS_serving_hb_interval": 0.3,
    "FLAGS_serving_hb_timeout": 2.0,
    # where the fleet coordinator publishes the live endpoints JSON
    # (clients re-read it to fail over); empty = no file
    "FLAGS_serving_endpoints_file": "",
    # -- serving control plane (tiers / autoscale / rollout) -----------------
    # SLO tiers: "tier:weight" comma list.  A request's tier scales its
    # admission deadline budget (shed when projected wait > deadline x
    # weight) and orders both batch assembly and queue-full eviction, so
    # under overload the lowest-weight tier sheds first.  Requests with
    # no tier get weight 1.0 (pre-tier behavior); an unknown tier name
    # defensively gets the lowest configured weight.
    "FLAGS_serving_tier_weights": "paid:1.0,free:0.45,batch:0.15",
    # ServingClient: how many times a shed reply is retried client-side
    # after its retry_after_ms hint (with backoff+jitter) before the shed
    # is surfaced to the caller; 0 restores the old return-immediately
    "FLAGS_serving_client_shed_retries": 2,
    # replica autoscaler (serving/fleet.py AutoScaler, tools/serve.py
    # --autoscale): poll period (s); consecutive pressure/idle polls
    # before scaling (hysteresis); post-action cooldown polls; the mean
    # queue depth that counts as pressure; and the replica count clamp
    "FLAGS_serving_autoscale_interval": 0.5,
    "FLAGS_serving_scale_up_ticks": 3,
    "FLAGS_serving_scale_down_ticks": 8,
    "FLAGS_serving_autoscale_cooldown": 6,
    "FLAGS_serving_scale_up_depth": 4.0,
    "FLAGS_serving_min_replicas": 1,
    "FLAGS_serving_max_replicas": 4,
    # versioned rollout (serving/rollout.py): default canary traffic
    # fraction, and the auto-rollback gate — trips when the canary's
    # phase p99 exceeds ratio x the baseline version's, or its per-
    # request error rate exceeds the cap, judged only after min_samples
    # canary requests have completed
    "FLAGS_serving_canary_fraction": 0.25,
    "FLAGS_rollout_gate_p99_ratio": 2.0,
    "FLAGS_rollout_gate_error_rate": 0.05,
    "FLAGS_rollout_gate_min_samples": 20,
    # -- fleet observability (serving/fleetmon.py FleetMonitor) --------------
    # scrape/aggregate cadence (s) and the trailing horizon (s) used for
    # windowed rates derived from the per-process time-series ring
    # (per-tier shed/s on the 1s republish, autoscaler fleet rates)
    "FLAGS_serving_fleetmon_interval": 1.0,
    "FLAGS_serving_rate_window": 30.0,
    # burn-rate SLO rules: ";"-separated "name:metric:pQQ:objective_ms".
    # metric is a histogram flat key or prefix (label sets merge), e.g.
    # "paid_server:server_ms{tier=paid}:p99:500" alerts when the paid
    # tier's windowed server-side p99 burns past 500 ms.  Each rule is
    # evaluated over a fast AND a slow trailing window (multi-window
    # burn-rate alerting): the alert FIRES when both windows' burn
    # (windowed pQQ / objective) reach the threshold, and CLEARS with
    # hysteresis once the fast window drops below threshold x clear_ratio
    "FLAGS_serving_slo_rules":
        "paid_server:server_ms{tier=paid}:p99:500;decode_itl:itl_ms:p99:250",
    "FLAGS_serving_slo_fast_window": 60.0,
    "FLAGS_serving_slo_slow_window": 900.0,
    "FLAGS_serving_slo_burn_threshold": 1.0,
    "FLAGS_serving_slo_clear_ratio": 0.5,
    # bounded length of the in-process telemetry time-series ring (one
    # sample per publisher tick; 1024 ~= 17 min of 1s samples)
    "FLAGS_telemetry_series_cap": 1024,
    # -- autoregressive decode serving (serving/kv_cache.py + DecodeEngine) --
    # decode-lane buckets: the running token batch pads to the smallest
    # bucket that fits the live sequences; one decode-step executable is
    # AOT-compiled per bucket at prewarm, so mixed-length traffic never
    # triggers a runtime XLA compile
    "FLAGS_serving_decode_buckets": "4,8",
    # "token" = continuous batching at token granularity (sequences
    # join/leave the running batch at every decode step); "request" =
    # request-level static batching (the batch drains fully before new
    # sequences join) — kept as the loadgen comparison baseline
    "FLAGS_serving_decode_mode": "token",
    # paged KV-cache geometry: tokens per block, and how many blocks the
    # engine owns per model.  0 blocks = size from FLAGS_hbm_budget_bytes
    # (kv_cache.plan_num_blocks), falling back to 64 when no budget is set.
    "FLAGS_kv_block_size": 16,
    "FLAGS_kv_cache_blocks": 0,
    # KV-block residency dtype: f32 (bitwise parity with the unpaged
    # reference) or int8 (quantize-for-the-residency, EQuARX idiom: per
    # (block, position, head) max-abs scales; ~4x the f32 capacity per
    # byte of HBM at a small accuracy cost)
    "FLAGS_kv_cache_dtype": "f32",
    # opt-in Pallas paged-attention gather kernel
    # (pallas_kernels/paged_attention.py): scalar-prefetched block tables
    # steer the K/V block DMA so the gathered [B, S, H, D] intermediate
    # never materializes in HBM.  Probe-gated like every PR-9 kernel.
    "FLAGS_use_pallas_paged_attention": False,
    # draft-model speculative decoding on the paged decode path
    # (DecodeEngine): 0 = off; k > 0 runs the model's bundled draft
    # decoder (save_decoder(draft=...) / <model_dir>/draft) k tokens
    # ahead per sequence through its own paged KV lanes, then verifies
    # all k+1 positions with ONE bucketed multi-token target step.
    # Greedy verification accepts the longest draft prefix matching the
    # target argmax chain, so output stays bitwise-equal to k=0;
    # rollback is free (context_lens truncation + same-iteration block
    # free).  Requires a draft bundle — a model without one decodes
    # non-speculatively regardless of k.
    "FLAGS_speculative_k": 0,
    # content-addressed KV prefix caching over the paged pool: admission
    # matches each prompt's hash chain against sealed full-prompt blocks,
    # seeds the block table with the shared prefix, and prefill computes
    # only the uncached tail.  Zero-ref cached blocks park in an LRU
    # evictable pool (reclaimed on demand), so residency is free under
    # pressure; outputs stay bitwise-identical cache-on vs cache-off.
    "FLAGS_prefix_cache": True,
    # live decode-session migration (serving/migrate.py): on, the engine
    # publishes each COMPLETED decode-history block into the prefix index
    # under the full-history hash chain (prompt ++ emitted tokens), so a
    # crash-resume (`__resume__`) or migrated session re-prefills only
    # the tokens since the last sealed block; the server also accepts
    # kind=session `__kvxfer__` frames and resume submissions.  Off, the
    # wire rejects session frames and resume falls back to full replay.
    "FLAGS_session_migration": True,
    # drain-by-migration: a retiring replica (autoscale-down, rollout
    # flip) pushes its live decode sessions to peers at a batch boundary
    # instead of waiting out long generations.  Off by default — flipped
    # on by the --migrate-smoke CI leg and opt-in deployments.
    "FLAGS_migrate_on_drain": False,
    # pressure-trigger migration: mid-decode preemption may migrate the
    # youngest (preempted) sequence to the least-loaded peer (fleetmon's
    # windowed occupancy signal) instead of deterministic local
    # recompute.  Off by default; recompute is always the fallback.
    "FLAGS_migrate_on_pressure": False,
    # seconds a migration source waits for the destination's
    # __resumeack__ before aborting the hand-off and resuming locally
    "FLAGS_migrate_ack_timeout": 10.0,
    # cap on total prefill tokens mixed into one decode iteration
    # (0 = unlimited).  Under a long-prompt burst, unbudgeted prefill
    # chunks crowd every iteration and inflate decode ITL p99; the budget
    # round-robins prefilling lanes so decode lanes always run.  Pure
    # scheduling: compiles nothing new (misses stay flat).
    "FLAGS_decode_prefill_token_budget": 0,
    # accepted no-ops (XLA/PJRT owns these concerns; benchmark's per-op
    # sync has no meaning under whole-block compilation)
    "FLAGS_benchmark": False,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_fuse_parameter_memory_size": -1,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_enable_parallel_graph": False,
    "FLAGS_use_system_allocator": False,
}

_flags = {}


def _coerce(cur_default, value):
    if isinstance(cur_default, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes")
        return bool(value)
    if isinstance(cur_default, float):
        return float(value)
    if isinstance(cur_default, int):
        return int(value)
    return value


def _init_from_env():
    for k, dflt in _DEFAULTS.items():
        env = os.environ.get(k)
        _flags[k] = _coerce(dflt, env) if env is not None else dflt


_init_from_env()


def _norm(name):
    return name if name.startswith("FLAGS_") else "FLAGS_" + name


def set_flags(flags):
    """fluid.set_flags({'FLAGS_check_nan_inf': True}).  Unknown names raise
    (matching the reference's gflags registry check) so typos can't silently
    disable a debug flag."""
    for k, v in flags.items():
        k = _norm(k)
        if k not in _DEFAULTS:
            raise ValueError(
                "unknown flag %r (known: %s)" % (k, ", ".join(sorted(_DEFAULTS))))
        _flags[k] = _coerce(_DEFAULTS[k], v)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {(_norm(n)): _flags.get(_norm(n)) for n in names}


def flag(name):
    """Internal fast read."""
    return _flags.get(_norm(name))
