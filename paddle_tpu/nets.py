"""Composite network blocks (reference python/paddle/fluid/nets.py:
simple_img_conv_pool:28, img_conv_group:138, sequence_conv_pool:251,
glu:319, scaled_dot_product_attention:360) — pure compositions of
fluid.layers, used heavily by the book models."""

from . import layers

__all__ = [
    "simple_img_conv_pool", "sequence_conv_pool", "glu",
    "scaled_dot_product_attention", "img_conv_group",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stacked conv [+BN] [+dropout] blocks followed by one pool
    (nets.py:138, the VGG building block)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v, name):
        if isinstance(v, (list, tuple)):
            assert len(v) == len(conv_num_filter), (
                "%s length %d must match conv_num_filter length %d"
                % (name, len(v), len(conv_num_filter)))
            return list(v)
        return [v] * len(conv_num_filter)

    conv_padding = _expand(conv_padding, "conv_padding")
    conv_filter_size = _expand(conv_filter_size, "conv_filter_size")
    param_attr = _expand(param_attr, "param_attr")
    conv_with_batchnorm = _expand(conv_with_batchnorm,
                                  "conv_with_batchnorm")
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate,
                                       "conv_batchnorm_drop_rate")

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    conv_out = layers.sequence_conv(
        input=input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated linear unit: split in half along dim, a * sigmoid(b)
    (nets.py:319)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(x=a, y=layers.sigmoid(x=b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention (nets.py:360): q/k/v are
    [B, T, D]; returns [B, Tq, Dv] context."""
    for name, t in (("queries", queries), ("keys", keys),
                    ("values", values)):
        if t.shape is None or len(t.shape) != 3:
            raise ValueError(
                "%s must be a 3-D [batch, time, hidden] tensor, got shape "
                "%s" % (name, t.shape))
    if not (queries.shape[-1] % num_heads == 0
            and values.shape[-1] % num_heads == 0):
        raise ValueError(
            "num_heads (%d) must divide the hidden sizes (%s, %s)"
            % (num_heads, queries.shape[-1], values.shape[-1]))

    def _split_heads(x):
        if num_heads == 1:
            return x
        B_T_D = x.shape
        reshaped = layers.reshape(
            x, shape=[B_T_D[0] or -1, B_T_D[1], num_heads,
                      B_T_D[2] // num_heads])
        return layers.transpose(reshaped, perm=[0, 2, 1, 3])

    def _merge_heads(x):
        if num_heads == 1:
            return x
        t = layers.transpose(x, perm=[0, 2, 1, 3])
        s = t.shape
        return layers.reshape(t, shape=[s[0] or -1, s[1], s[2] * s[3]])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    key_dim = float(queries.shape[-1] // num_heads)
    scaled_q = layers.scale(q, scale=key_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx = layers.matmul(weights, v)
    return _merge_heads(ctx)
