"""LayerHelper: shared parameter/bias/activation plumbing for layers.

Parity: python/paddle/fluid/layer_helper.py — creates parameters with their
initializers (ops into the startup program), temp variables, bias ops, and
activation ops.
"""

from .framework import default_main_program, default_startup_program
from .initializer import Constant, Xavier
from .param_attr import ParamAttr
from .utils import unique_name

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    # -- inputs --------------------------------------------------------------
    def input(self, input_param_name="input"):
        return self.kwargs[input_param_name]

    def input_dtype(self, input_param_name="input"):
        inputs = self.kwargs[input_param_name]
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("mixed input dtypes: %s vs %s" % (dtype, each.dtype))
        return dtype

    # -- params --------------------------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = unique_name.generate(
                "%s.%s" % (self.name, "b" if is_bias else "w")
            )
        # shared parameters (an explicit attr.name reused across layers,
        # e.g. word2vec's one embedding table behind four lookups) must
        # resolve to the ONE existing Parameter — re-creating it appended a
        # duplicate initializer op into the startup program per reuse
        # (N racing writes to one var; the verifier's DA003 flags it)
        existing = self.main_program.global_block().vars.get(attr.name)
        if existing is not None:
            from .framework import Parameter

            if not isinstance(existing, Parameter):
                raise ValueError(
                    "variable %r already exists and is not a Parameter"
                    % attr.name)
            if tuple(existing.shape) != tuple(shape):
                raise ValueError(
                    "shared parameter %r re-requested with shape %s != %s"
                    % (attr.name, list(shape), list(existing.shape)))
            return existing
        if default_initializer is None:
            default_initializer = Constant(0.0) if is_bias else Xavier()
        init = attr.initializer or default_initializer
        param = self.block.create_parameter(
            shape=shape, dtype=dtype, initializer=init,
            **attr._to_kwargs()
        )
        init(param)  # appends the init op to the startup program
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, **kwargs):
        return self.block.create_var(**kwargs)

    def create_global_variable(self, persistable=False, **kwargs):
        if not kwargs.get("name"):
            kwargs["name"] = unique_name.generate(".".join([self.name, "tmp"]))
        return self.main_program.global_block().create_var(
            persistable=persistable, **kwargs
        )

    def create_or_get_global_variable(self, name, **kwargs):
        gblock = self.main_program.global_block()
        if gblock.has_var(name):
            return gblock.vars[name]
        return gblock.create_var(name=name, **kwargs)

    def set_variable_initializer(self, var, initializer):
        initializer(var)
        return var

    # -- ops -----------------------------------------------------------------
    def append_op(self, **kwargs):
        return self.block.append_op(**kwargs)

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(
            attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True
        )
        if b is None:
            return input_var
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var, act=None):
        if act is None:
            act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [tmp]},
            attrs=act,
        )
        return tmp
