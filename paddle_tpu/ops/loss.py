"""Loss ops: softmax_with_cross_entropy, cross_entropy, and friends.

Parity: softmax_with_cross_entropy_op.cc, cross_entropy_op.cc,
sigmoid_cross_entropy_with_logits_op.cc, huber_loss_op.cc,
smooth_l1_loss_op.cc (paddle/fluid/operators/).
"""

import functools

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _swce_hard_loss(logits, label, ax, ignore_index):
    return _swce_hard_fwd(logits, label, ax, ignore_index)[0]


def _swce_hard_fwd(logits, label, ax, ignore_index):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax)
    picked = _take_label(logp, label, ax)
    loss = -picked
    # mask wherever label == ignore_index REGARDLESS of sign — the
    # reference kernel's semantics; -100 is the common padding convention
    lab = (label if label.ndim == loss.ndim
           else jnp.expand_dims(label, ax))
    loss = jnp.where(lab == ignore_index, 0.0, loss)
    # The ONLY large backward residual is the softmax, stored in the
    # logits' carry dtype: at the BERT MLM-head shape ([~4.9k, 30522])
    # the default f32 residual is ~600 MB; bf16 halves it, consistent
    # with the bf16-carry AMP policy (the LOSS stays f32-exact — it is
    # computed from the f32 log_softmax above).
    return loss, (jnp.exp(logp).astype(logits.dtype), label)


def _swce_hard_bwd(ax, ignore_index, res, dloss):
    sm, label = res
    lab = label if label.ndim == sm.ndim else jnp.expand_dims(label, ax)
    # onehot by iota-compare, NOT scatter: a [4915, 30522] put_along_axis
    # measured ~+50 ms on the BERT step (TPU scatters serialize); the
    # compare fuses into the same elementwise pass
    cls = jax.lax.broadcasted_iota(jnp.int32, sm.shape, ax)
    onehot = (cls == lab.astype(jnp.int32)).astype(jnp.float32)
    d = (sm.astype(jnp.float32) - onehot) * dloss.astype(jnp.float32)
    d = jnp.where(lab == ignore_index, 0.0, d)  # any-sign ignore_index
    return d.astype(sm.dtype), None


_swce_hard_loss.defvjp(_swce_hard_fwd, _swce_hard_bwd)


def _take_label(logp, label, axis):
    """Gather logp at integer labels along axis; label has a trailing 1 dim
    (fluid convention) or matches logp without the class axis.  Labels are
    clipped into range so ignored entries (e.g. the -100 padding
    convention) gather safely — callers mask their loss to zero."""
    lab = label
    if not (lab.shape == logp.shape[:axis] + (1,) + logp.shape[axis + 1:]
            or (lab.ndim == logp.ndim and lab.shape[axis] == 1)):
        lab = jnp.expand_dims(lab, axis)
    safe = jnp.clip(lab.astype(jnp.int32), 0, logp.shape[axis] - 1)
    return jnp.take_along_axis(logp, safe, axis=axis)


@register_op(
    "softmax_with_cross_entropy",
    inputs=("Logits", "Label"),
    outputs=("Softmax", "Loss"),
    attrs={"soft_label": False, "ignore_index": -100, "numeric_stable_mode": True,
           "axis": -1},
    no_grad_inputs=("Label",),
)
def softmax_with_cross_entropy(ctx, logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               axis=-1):
    ax = axis if axis >= 0 else logits.ndim + axis
    # the loss head always computes in f32: under the bf16-carry AMP policy
    # logits arrive as bf16, and an 8-bit-mantissa log-softmax would cost
    # loss-curve parity (BASELINE.md tolerance tiers)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax)
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=ax, keepdims=True)
        # same Softmax-output contract as the hard path: the reference
        # grad op drops Softmax@GRAD in both label modes
        return jax.lax.stop_gradient(softmax), loss
    # hard labels: custom vjp whose only large residual is the softmax in
    # the logits' CARRY dtype (f32 stays f32; bf16 halves the ~600 MB
    # MLM-head residual).  The Softmax output is the reference's
    # intermediate (not differentiated through) — stop_gradient matches
    # its no-second-use contract while keeping the value available.
    loss = _swce_hard_loss(logits, label, ax, ignore_index)
    return jax.lax.stop_gradient(softmax), loss


@register_op(
    "cross_entropy",
    inputs=("X", "Label"),
    outputs=("Y",),
    attrs={"soft_label": False, "ignore_index": -100},
    no_grad_inputs=("Label",),
)
def cross_entropy(ctx, x, label, soft_label=False, ignore_index=-100):
    logp = jnp.log(jnp.clip(x.astype(jnp.float32), 1e-20, None))
    if soft_label:
        return -jnp.sum(label * logp, axis=-1, keepdims=True)
    picked = _take_label(logp, label, x.ndim - 1)
    loss = -picked
    lab = label if label.ndim == loss.ndim else jnp.expand_dims(label, -1)
    loss = jnp.where(lab == ignore_index, 0.0, loss)  # any-sign ignore
    return loss


@register_op(
    "cross_entropy2",
    inputs=("X", "Label"),
    outputs=("Y", "XShape", "MatchX"),
    attrs={"ignore_index": -100},
    no_grad_inputs=("Label",),
)
def cross_entropy2(ctx, x, label, ignore_index=-100):
    logp = jnp.log(jnp.clip(x, 1e-20, None))
    picked = _take_label(logp, label, x.ndim - 1)
    lab = (label if label.ndim == picked.ndim
           else jnp.expand_dims(label, -1))
    ignored = lab == ignore_index
    # masked rows: loss 0, MatchX 1 (the reference's ignored-row fill)
    return (jnp.where(ignored, 0.0, -picked), None,
            jnp.where(ignored, 1.0, jnp.exp(picked)))


@register_op(
    "sigmoid_cross_entropy_with_logits",
    inputs=("X", "Label"),
    outputs=("Out",),
    attrs={"ignore_index": -100, "normalize": False},
    no_grad_inputs=("Label",),
)
def sigmoid_cross_entropy_with_logits(ctx, x, label, ignore_index=-100,
                                      normalize=False):
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
    return loss


@register_op(
    "huber_loss",
    inputs=("X", "Y"),
    outputs=("Residual", "Out"),
    attrs={"delta": 1.0},
    no_grad_inputs=("Y",),
)
def huber_loss(ctx, x, y, delta=1.0):
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return r, out


@register_op(
    "smooth_l1_loss",
    inputs=("X", "Y", "InsideWeight", "OutsideWeight"),
    outputs=("Diff", "Out"),
    attrs={"sigma": 1.0},
    optional_inputs=("InsideWeight", "OutsideWeight"),
    no_grad_inputs=("Y", "InsideWeight", "OutsideWeight"),
)
def smooth_l1_loss(ctx, x, y, iw, ow, sigma=1.0):
    s2 = sigma * sigma
    diff = x - y
    if iw is not None:
        diff = diff * iw
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ow is not None:
        val = val * ow
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return diff, out


@register_op(
    "kldiv_loss",
    inputs=("X", "Target"),
    outputs=("Loss",),
    attrs={"reduction": "mean"},
    no_grad_inputs=("Target",),
)
def kldiv_loss(ctx, x, target, reduction="mean"):
    loss = jnp.where(target > 0, target * (jnp.log(target) - x), 0.0)
    if reduction == "mean":
        return jnp.mean(loss).reshape((1,))
    if reduction == "sum":
        return jnp.sum(loss).reshape((1,))
    if reduction == "batchmean":
        return (jnp.sum(loss) / x.shape[0]).reshape((1,))
    return loss


@register_op(
    "log_loss",
    inputs=("Predicted", "Labels"),
    outputs=("Loss",),
    attrs={"epsilon": 1e-4},
    no_grad_inputs=("Labels",),
)
def log_loss(ctx, pred, label, epsilon=1e-4):
    return -label * jnp.log(pred + epsilon) - (1.0 - label) * jnp.log(
        1.0 - pred + epsilon
    )


@register_op(
    "mse_loss",
    inputs=("X", "Y"),
    outputs=("Out",),
    no_grad_inputs=("Y",),
)
def mse_loss(ctx, x, y):
    return jnp.square(x - y)
