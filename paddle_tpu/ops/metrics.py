"""Metric + comparison/logical ops.

Parity: operators/metrics/ (accuracy_op.cc, auc_op.cc), top_k_op.cc,
arg_max_op.cc, arg_min_op.cc, compare_op.cc, logical_op.cc, isfinite_op.cc.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("top_k", inputs=("X", "K"), outputs=("Out", "Indices"),
             attrs={"k": 1}, optional_inputs=("K",), grad_maker="auto")
def top_k(ctx, x, k_t, k=1):
    if k_t is not None:
        k = int(k_t.reshape(()))  # requires concrete K on TPU
    vals, idx = jax.lax.top_k(x, k)
    return vals, idx.astype(jnp.int64)


@register_op("top_k_v2", inputs=("X", "K"), outputs=("Out", "Indices"),
             attrs={"k": 1, "axis": -1, "largest": True, "sorted": True},
             optional_inputs=("K",))
def top_k_v2(ctx, x, k_t, k=1, axis=-1, largest=True, sorted=True):
    if k_t is not None:
        k = int(k_t.reshape(()))
    ax = axis if axis >= 0 else x.ndim + axis
    moved = jnp.moveaxis(x, ax, -1)
    if not largest:
        moved = -moved
    vals, idx = jax.lax.top_k(moved, k)
    if not largest:
        vals = -vals
    return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"), grad_maker=None)
def accuracy(ctx, out, indices, label):
    n = indices.shape[0]
    lab = label.reshape(n, 1)
    correct = jnp.any(indices == lab, axis=1).sum()
    return (
        (correct / n).astype(jnp.float32).reshape((1,)),
        correct.astype(jnp.int32).reshape((1,)),
        jnp.asarray([n], dtype=jnp.int32),
    )


@register_op("arg_max", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "keepdims": False, "dtype": 3, "flatten": False},
             grad_maker=None)
def arg_max(ctx, x, axis=-1, keepdims=False, dtype=3, flatten=False):
    if flatten:
        x = x.reshape(-1)
        axis = 0
    return jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.int64)


@register_op("arg_min", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "keepdims": False, "dtype": 3, "flatten": False},
             grad_maker=None)
def arg_min(ctx, x, axis=-1, keepdims=False, dtype=3, flatten=False):
    if flatten:
        x = x.reshape(-1)
        axis = 0
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.int64)


@register_op("argsort", inputs=("X",), outputs=("Out", "Indices"),
             attrs={"axis": -1, "descending": False}, grad_maker=None)
def argsort(ctx, x, axis=-1, descending=False):
    idx = jnp.argsort(-x if descending else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out, idx.astype(jnp.int64)


def _register_compare(name, fn):
    @register_op(name, inputs=("X", "Y"), outputs=("Out",),
                 attrs={"axis": -1, "force_cpu": False}, grad_maker=None)
    def _low(ctx, x, y, axis=-1, force_cpu=False, _fn=fn):
        return _fn(x, y)

    return _low


_register_compare("equal", jnp.equal)
_register_compare("not_equal", jnp.not_equal)
_register_compare("less_than", jnp.less)
_register_compare("less_equal", jnp.less_equal)
_register_compare("greater_than", jnp.greater)
_register_compare("greater_equal", jnp.greater_equal)


def _register_logical(name, fn, binary=True):
    if binary:
        @register_op(name, inputs=("X", "Y"), outputs=("Out",), grad_maker=None)
        def _low(ctx, x, y, _fn=fn):
            return _fn(x, y)
    else:
        @register_op(name, inputs=("X",), outputs=("Out",), grad_maker=None)
        def _low(ctx, x, _fn=fn):
            return _fn(x)
    return _low


_register_logical("logical_and", jnp.logical_and)
_register_logical("logical_or", jnp.logical_or)
_register_logical("logical_xor", jnp.logical_xor)
_register_logical("logical_not", jnp.logical_not, binary=False)


@register_op("isfinite", inputs=("X",), outputs=("Out",), grad_maker=None,
             duplicable_inputs=("X",))
def isfinite(ctx, xs):
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return ok.reshape((1,))


@register_op("isfinite_v2", inputs=("X",), outputs=("Out",), grad_maker=None)
def isfinite_v2(ctx, x):
    return jnp.isfinite(x)


@register_op("isnan_v2", inputs=("X",), outputs=("Out",), grad_maker=None)
def isnan_v2(ctx, x):
    return jnp.isnan(x)


@register_op("isinf_v2", inputs=("X",), outputs=("Out",), grad_maker=None)
def isinf_v2(ctx, x):
    return jnp.isinf(x)


@register_op(
    "auc",
    inputs=("Predict", "Label", "StatPos", "StatNeg"),
    outputs=("AUC", "StatPosOut", "StatNegOut"),
    attrs={"curve": "ROC", "num_thresholds": 4095, "slide_steps": 1},
    grad_maker=None,
)
def auc(ctx, predict, label, stat_pos, stat_neg, curve="ROC",
        num_thresholds=4095, slide_steps=1):
    """Streaming AUC via threshold buckets (metrics/auc_op.h)."""
    pos_prob = predict[:, 1] if predict.ndim == 2 and predict.shape[1] == 2 else predict.reshape(-1)
    bucket = jnp.clip(
        (pos_prob * num_thresholds).astype(jnp.int32), 0, num_thresholds
    )
    lab = label.reshape(-1).astype(jnp.int32)
    pos_hist = jnp.zeros(num_thresholds + 1, jnp.int64).at[bucket].add(lab)
    neg_hist = jnp.zeros(num_thresholds + 1, jnp.int64).at[bucket].add(1 - lab)
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # integrate: sum over thresholds of tp/fp trapezoid
    tp = jnp.cumsum(new_pos[::-1])[::-1].astype(jnp.float64)
    fp = jnp.cumsum(new_neg[::-1])[::-1].astype(jnp.float64)
    tot_pos = tp[0]
    tot_neg = fp[0]
    # pairs: area via rank-sum equivalent
    neg_below = jnp.cumsum(new_neg) - new_neg
    auc_val = jnp.sum(
        new_pos.astype(jnp.float64)
        * (neg_below.astype(jnp.float64) + new_neg.astype(jnp.float64) * 0.5)
    )
    denom = jnp.maximum(tot_pos * tot_neg, 1.0)
    return (
        (auc_val / denom).astype(jnp.float64).reshape((1,)),
        new_pos,
        new_neg,
    )
