"""Linear-chain CRF ops (parity: paddle/fluid/operators/linear_chain_crf_op.cc,
crf_decoding_op.cc).

Dense [B, T, C] emissions with int length mask replace the reference's LoD
batching.  Transition layout follows the reference: row 0 = start weights,
row 1 = stop weights, rows 2.. = [C, C] transition matrix.  Forward
(log-likelihood) runs as a lax.scan over time — differentiable, so the grad
comes from the auto vjp; decoding is a Viterbi scan + backtrack.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


def _split_transition(transition):
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]
    return start, stop, trans


@register_op("linear_chain_crf", inputs=("Emission", "Transition", "Label",
                                         "Length"),
             outputs=("Alpha", "EmissionExps", "TransitionExps",
                      "LogLikelihood"),
             optional_inputs=("Length",),
             no_grad_inputs=("Label", "Length"))
def linear_chain_crf(ctx, emission, transition, label, length=None):
    """Negative log-likelihood of label paths under a linear-chain CRF.

    emission [B, T, C] (or [T, C] for one sequence), label [B, T]/[B, T, 1],
    length [B] valid steps (None = all T).  Returns per-sequence NLL
    [B, 1] in the LogLikelihood slot (matching the reference's sign: the
    op's output is minimized directly).
    """
    if emission.ndim == 2:
        emission = emission[None]
    B, T, C = emission.shape
    if label.ndim == 3:
        label = label[..., 0]
    if label.ndim == 1:
        label = label[None]
    label = label.astype(jnp.int32)
    start, stop, trans = _split_transition(transition)
    em = emission.astype(jnp.float32)
    if length is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = length.reshape(-1).astype(jnp.int32)

    # ---- partition function: forward algorithm over time ------------------
    alpha0 = start[None, :] + em[:, 0, :]                     # [B, C]

    def fwd(alpha, t):
        # [B, C_prev] -> [B, C]: logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.nn.logsumexp(scores, axis=1) + em[:, t, :]
        keep = (t < lens)[:, None]
        return jnp.where(keep, new, alpha), alpha

    alpha_final, alphas = lax.scan(fwd, alpha0, jnp.arange(1, T))
    logZ = jax.nn.logsumexp(alpha_final + stop[None, :], axis=1)

    # ---- score of the gold path -------------------------------------------
    b_idx = jnp.arange(B)
    first_em = em[:, 0, :][b_idx, label[:, 0]]
    gold = start[label[:, 0]] + first_em

    def gold_step(g, t):
        prev = label[:, t - 1]
        cur = label[:, t]
        add = trans[prev, cur] + em[:, t, :][b_idx, cur]
        return g + jnp.where(t < lens, add, 0.0), None

    gold, _ = lax.scan(gold_step, gold, jnp.arange(1, T))
    last_idx = jnp.clip(lens - 1, 0, T - 1)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = gold + stop[last_tag]

    nll = (logZ - gold)[:, None]
    # Alpha / exps outputs kept for API parity (consumed by nothing on TPU —
    # the grad comes from the auto vjp of this forward)
    return (jnp.concatenate([alpha0[:, None, :],
                             jnp.swapaxes(alphas, 0, 1)], axis=1),
            jnp.exp(em), jnp.exp(transition), nll)


@register_op("crf_decoding", inputs=("Emission", "Transition", "Label",
                                     "Length"),
             outputs=("ViterbiPath",),
             optional_inputs=("Label", "Length"), grad_maker=None)
def crf_decoding(ctx, emission, transition, label=None, length=None):
    """Viterbi decode (crf_decoding_op.cc).  With Label given, emits 1 where
    the decoded tag disagrees with the label (the reference's error-mask
    mode); otherwise the best tag path [B, T]."""
    if emission.ndim == 2:
        emission = emission[None]
    B, T, C = emission.shape
    start, stop, trans = _split_transition(transition)
    em = emission.astype(jnp.float32)
    if length is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = length.reshape(-1).astype(jnp.int32)

    v0 = start[None, :] + em[:, 0, :]

    def vit(v, t):
        scores = v[:, :, None] + trans[None, :, :]          # [B, Cp, C]
        best_prev = jnp.argmax(scores, axis=1)              # [B, C]
        new = jnp.max(scores, axis=1) + em[:, t, :]
        keep = (t < lens)[:, None]
        return jnp.where(keep, new, v), best_prev

    v_final, backptrs = lax.scan(vit, v0, jnp.arange(1, T))  # [T-1, B, C]
    last = jnp.argmax(v_final + stop[None, :], axis=1)       # [B]

    # walk from T-2 down to 0 emitting the tag at step t+1;
    # backptrs[t] maps tags at step t+1 -> best tag at step t
    def back_scan(tag, t):
        bp = backptrs[t]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        within = (t + 1) < lens
        new_tag = jnp.where(within, prev, tag)
        return new_tag, tag

    tag_T, emitted = lax.scan(back_scan, last, jnp.arange(T - 2, -1, -1))
    # emitted holds tags for steps T-1..1 (in reverse); prepend step 0
    path = jnp.concatenate([tag_T[None, :], emitted[::-1]], axis=0)  # [T, B]
    path = jnp.swapaxes(path, 0, 1).astype(jnp.int64)                # [B, T]
    if label is not None:
        if label.ndim == 3:
            label = label[..., 0]
        if label.ndim == 1:
            label = label[None]
        return (path != label.astype(jnp.int64)).astype(jnp.int64)
    return path
