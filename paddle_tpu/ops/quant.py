"""Fake-quantization ops for quantization-aware training.

Parity (paddle/fluid/operators/): fake_quantize_op.cc
(fake_quantize_abs_max, fake_quantize_moving_average_abs_max,
fake_channel_wise_quantize_abs_max, fake_quantize_range_abs_max) and
fake_dequantize_op.cc.  Quantize+dequantize in one op (the QAT contract):
forward rounds through the int grid, backward is straight-through
(identity), implemented with a custom grad that passes dY through.
"""

import jax
import jax.numpy as jnp

from ..core.registry import GradOpDesc, register_op
from ..framework import _grad_var_name


def _ste_grad(op, no_grad_set):
    """Straight-through estimator: dX = dOut (fake_quantize_op grad)."""
    out_name = op.output("Out")[0]
    x_name = op.input("X")[0]
    return [GradOpDesc(
        "assign", inputs={"X": [_grad_var_name(out_name)]},
        outputs={"Out": [_grad_var_name(x_name)]})]


def _quant_dequant(x, scale, bit_length):
    bnt = (1 << (bit_length - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bnt) * s / bnt


@register_op("fake_quantize_abs_max", inputs=("X",),
             outputs=("Out", "OutScale"), attrs={"bit_length": 8},
             grad_maker=_ste_grad)
def fake_quantize_abs_max(ctx, x, bit_length=8):
    scale = jnp.max(jnp.abs(x))
    return _quant_dequant(x, scale, bit_length), scale.reshape(1)


@register_op("fake_channel_wise_quantize_abs_max", inputs=("X",),
             outputs=("Out", "OutScale"),
             attrs={"bit_length": 8, "quant_axis": 0},
             grad_maker=_ste_grad)
def fake_channel_wise_quantize_abs_max(ctx, x, bit_length=8, quant_axis=0):
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = x.shape[quant_axis]
    return (_quant_dequant(x, scale.reshape(shape), bit_length), scale)


@register_op("fake_quantize_moving_average_abs_max",
             inputs=("X", "InScale", "InAccum", "InState"),
             outputs=("Out", "OutScale", "OutAccum", "OutState"),
             attrs={"bit_length": 8, "moving_rate": 0.9, "is_test": False},
             optional_inputs=("InAccum", "InState"),
             no_grad_inputs=("InScale", "InAccum", "InState"),
             grad_maker=_ste_grad)
def fake_quantize_moving_average_abs_max(ctx, x, in_scale, in_accum=None,
                                         in_state=None, bit_length=8,
                                         moving_rate=0.9, is_test=False):
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale.reshape(())
        accum, state = in_accum, in_state
    else:
        state0 = in_state.reshape(()) if in_state is not None else 1.0
        accum0 = in_accum.reshape(()) if in_accum is not None else \
            in_scale.reshape(())
        state = moving_rate * state0 + 1.0
        accum = moving_rate * accum0 + cur
        scale = accum / state
        accum = accum.reshape(1)
        state = jnp.asarray(state).reshape(1)
    return (_quant_dequant(x, scale, bit_length), jnp.asarray(scale).reshape(1),
            accum, state)


@register_op("fake_dequantize_max_abs", inputs=("X", "Scale"),
             outputs=("Out",), attrs={"max_range": 127.0},
             no_grad_inputs=("Scale",))
def fake_dequantize_max_abs(ctx, x, scale, max_range=127.0):
    return x.astype(jnp.float32) * scale.reshape(()) / max_range
