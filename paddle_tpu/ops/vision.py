"""Vision / 3-D / channel ops.

Parity targets (paddle/fluid/operators/): lrn_op.cc, affine_channel_op.cc,
shuffle_channel_op.cc, space_to_depth_op.cc, temporal_shift_op.cc,
grid_sampler_op.cc, affine_grid_op.cc, conv_op.cc (3d), pool_op.cc (3d),
row_conv_op.cc, bilinear_tensor_product_op.cc, spectral_norm_op.cc,
data_norm_op.cc, fsp_op.cc.  All are jnp/lax compositions XLA fuses; convs
ride the MXU.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"),
             attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75,
                    "data_format": "NCHW"})
def lrn(ctx, x, n=5, k=2.0, alpha=1e-4, beta=0.75, data_format="NCHW"):
    """Local response normalization across channels (lrn_op.cc)."""
    sq = jnp.square(x)
    half = n // 2
    # sum over a window of `n` channels via padded cumulative trick
    pad = [(0, 0)] * x.ndim
    c_ax = 1 if data_format == "NCHW" else x.ndim - 1
    pad[c_ax] = (half, n - 1 - half)
    sq_p = jnp.pad(sq, pad)
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + lax.slice_in_dim(sq_p, i, i + x.shape[c_ax], axis=c_ax)
    mid = k + alpha * acc
    return x / jnp.power(mid, beta), mid


@register_op("affine_channel", inputs=("X", "Scale", "Bias"),
             outputs=("Out",), attrs={"data_layout": "NCHW"})
def affine_channel(ctx, x, scale, bias, data_layout="NCHW"):
    shape = [1] * x.ndim
    c_ax = 1 if data_layout == "NCHW" else x.ndim - 1
    shape[c_ax] = x.shape[c_ax]
    return x * scale.reshape(shape) + bias.reshape(shape)


@register_op("shuffle_channel", inputs=("X",), outputs=("Out",),
             attrs={"group": 1})
def shuffle_channel(ctx, x, group=1):
    n, c, h, w = x.shape
    return x.reshape(n, group, c // group, h, w).swapaxes(1, 2).reshape(
        n, c, h, w)


@register_op("space_to_depth", inputs=("X",), outputs=("Out",),
             attrs={"blocksize": 2})
def space_to_depth(ctx, x, blocksize=2):
    n, c, h, w = x.shape
    b = blocksize
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("temporal_shift", inputs=("X",), outputs=("Out",),
             attrs={"seg_num": 1, "shift_ratio": 0.25})
def temporal_shift(ctx, x, seg_num=1, shift_ratio=0.25):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    fwd = jnp.concatenate(
        [jnp.zeros_like(x[:, :1, :c1]), x[:, :-1, :c1]], axis=1)
    bwd = jnp.concatenate(
        [x[:, 1:, c1:c2], jnp.zeros_like(x[:, :1, c1:c2])], axis=1)
    out = jnp.concatenate([fwd, bwd, x[:, :, c2:]], axis=2)
    return out.reshape(nt, c, h, w)


@register_op("grid_sampler", inputs=("X", "Grid"), outputs=("Output",),
             attrs={"align_corners": True, "mode": "bilinear",
                    "padding_mode": "zeros"})
def grid_sampler(ctx, x, grid, align_corners=True, mode="bilinear",
                 padding_mode="zeros"):
    """Bilinear grid sampling (grid_sampler_op.cc): x [N,C,H,W], grid
    [N,H',W',2] in [-1,1]."""
    N, C, H, W = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * 0.5 * (W - 1)
        fy = (gy + 1) * 0.5 * (H - 1)
    else:
        fx = ((gx + 1) * W - 1) * 0.5
        fy = ((gy + 1) * H - 1) * 0.5
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = fx - x0
    wy = fy - y0

    def sample(yi, xi):
        valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        # gather per batch: x [N,C,H,W], idx [N,H',W']
        g = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yc, xc)
        return g * valid[:, None].astype(x.dtype)

    v00 = sample(y0, x0)
    v01 = sample(y0, x0 + 1)
    v10 = sample(y0 + 1, x0)
    v11 = sample(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
            + v10 * (1 - wx) * wy + v11 * wx * wy)


@register_op("affine_grid", inputs=("Theta", "OutputShape"),
             outputs=("Output",),
             attrs={"align_corners": True, "output_shape": []},
             optional_inputs=("OutputShape",), no_grad_inputs=("OutputShape",))
def affine_grid(ctx, theta, out_shape=None, align_corners=True,
                output_shape=()):
    """[N,2,3] affine params -> [N,H,W,2] sampling grid."""
    if out_shape is not None:
        import numpy as _np

        shp = [int(v) for v in _np.asarray(out_shape)]
    else:
        shp = [int(v) for v in output_shape]
    N, _, H, W = shp
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
    else:
        ys = (jnp.arange(H) * 2 + 1) / H - 1
        xs = (jnp.arange(W) * 2 + 1) / W - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H,W,3]
    return jnp.einsum("hwk,nik->nhwi", base, theta.astype(jnp.float32))


# -- 3-D convolution / pooling ----------------------------------------------


@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1,
                    "data_format": "NCDHW"})
def conv3d(ctx, x, w, strides=(1, 1, 1), paddings=(0, 0, 0),
           dilations=(1, 1, 1), groups=1, data_format="NCDHW", **_):
    p = list(paddings)
    pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    amp = ctx is not None and ctx.amp_bf16() and x.dtype in (jnp.float32,
                                                             jnp.bfloat16)
    xc, wc = (x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)) if amp else (x, w)
    out = lax.conv_general_dilated(
        xc, wc, window_strides=tuple(strides), padding=pad,
        rhs_dilation=tuple(dilations), dimension_numbers=dn,
        feature_group_count=groups)
    return out if amp else out.astype(x.dtype)


@register_op("conv3d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1,
                    "data_format": "NCDHW", "output_size": []})
def conv3d_transpose(ctx, x, w, strides=(1, 1, 1), paddings=(0, 0, 0),
                     dilations=(1, 1, 1), groups=1, data_format="NCDHW",
                     output_size=(), **_):
    from .nn import _transpose_conv_extra_pad, _transpose_conv_filter

    p = list(paddings)
    kd, kh, kw = w.shape[2], w.shape[3], w.shape[4]
    pads = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    extra = [0, 0, 0]
    if output_size:
        extra = _transpose_conv_extra_pad(
            (x.shape[2], x.shape[3], x.shape[4]), (kd, kh, kw),
            tuple(strides), pads, list(dilations), output_size)
    wt = _transpose_conv_filter(w, groups, (2, 3, 4))
    dn = lax.conv_dimension_numbers(x.shape, wt.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    return lax.conv_general_dilated(
        x, wt, window_strides=(1, 1, 1),
        padding=[(kd - 1 - p[0], kd - 1 - p[0] + extra[0]),
                 (kh - 1 - p[1], kh - 1 - p[1] + extra[1]),
                 (kw - 1 - p[2], kw - 1 - p[2] + extra[2])],
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dilations),
        dimension_numbers=dn, feature_group_count=groups)


@register_op("pool3d", inputs=("X",), outputs=("Out",),
             attrs={"pooling_type": "max", "ksize": [1, 1, 1],
                    "strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "global_pooling": False, "ceil_mode": False,
                    "exclusive": True, "adaptive": False,
                    "data_format": "NCDHW"})
def pool3d(ctx, x, pooling_type="max", ksize=(1, 1, 1), strides=(1, 1, 1),
           paddings=(0, 0, 0), global_pooling=False, ceil_mode=False,
           exclusive=True, adaptive=False, data_format="NCDHW", **_):
    if global_pooling:
        fn = jnp.max if pooling_type == "max" else jnp.mean
        return fn(x, axis=(2, 3, 4), keepdims=True)
    if adaptive:
        od, oh, ow = int(ksize[0]), int(ksize[1]), int(ksize[2])
        N, C, D, H, W = x.shape
        r = x.reshape(N, C, od, D // od, oh, H // oh, ow, W // ow)
        fn = jnp.max if pooling_type == "max" else jnp.mean
        return fn(r, axis=(3, 5, 7))
    kd, kh, kw = [int(v) for v in ksize]
    sd, sh, sw = [int(v) for v in strides]
    pd, ph, pw = [int(v) for v in paddings]
    window = (1, 1, kd, kh, kw)
    strides_ = (1, 1, sd, sh, sw)
    pads = ((0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw))
    if pooling_type == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides_, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_, pads)
    return s / (kd * kh * kw)


@register_op("row_conv", inputs=("X", "Filter"), outputs=("Out",))
def row_conv(ctx, x, w):
    """Lookahead row convolution (row_conv_op.cc) on dense [B, T, D] input
    with filter [future_context+1, D] (LoD batching replaced by padding)."""
    ctx_len = w.shape[0]
    T = x.shape[1]
    pad = jnp.pad(x, ((0, 0), (0, ctx_len - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for i in range(ctx_len):
        out = out + pad[:, i:i + T, :] * w[i]
    return out


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"),
             outputs=("Out",), optional_inputs=("Bias",))
def bilinear_tensor_product(ctx, x, y, w, bias=None):
    """out[:, k] = x W_k y^T (bilinear_tensor_product_op.cc); W: [K, Dx, Dy]."""
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return out


@register_op("spectral_norm", inputs=("Weight", "U", "V"), outputs=("Out",),
             attrs={"dim": 0, "power_iters": 1, "eps": 1e-12},
             no_grad_inputs=("U", "V"))
def spectral_norm(ctx, w, u, v, dim=0, power_iters=1, eps=1e-12):
    """Weight / sigma_max(weight) via power iteration (spectral_norm_op.cc)."""
    shape = w.shape
    if dim != 0:
        perm = [dim] + [i for i in range(len(shape)) if i != dim]
        w_t = jnp.transpose(w, perm)
    else:
        w_t = w
    h = w_t.shape[0]
    mat = w_t.reshape(h, -1)
    for _ in range(max(power_iters, 1)):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ v
    out = w_t / sigma
    if dim != 0:
        inv = [perm.index(i) for i in range(len(shape))]
        out = jnp.transpose(out, inv)
    return out


@register_op("data_norm", inputs=("X", "BatchSize", "BatchSum",
                                  "BatchSquareSum"),
             outputs=("Y", "Means", "Scales"),
             attrs={"epsilon": 1e-4},
             no_grad_inputs=("BatchSize", "BatchSum", "BatchSquareSum"))
def data_norm(ctx, x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4):
    """Global data normalization from accumulated statistics
    (data_norm_op.cc — CTR feature scaling)."""
    means = batch_sum / batch_size
    scales = jnp.sqrt(batch_size / (batch_square_sum - batch_size * means ** 2
                                    + epsilon))
    return (x - means) * scales, means, scales


@register_op("fsp", inputs=("X", "Y"), outputs=("Out",))
def fsp(ctx, x, y):
    """Flow-of-solution-procedure matrix (fsp_op.cc, distillation):
    [N,Cx,H,W] x [N,Cy,H,W] -> [N,Cx,Cy]."""
    n, cx, h, w = x.shape
    return jnp.einsum("nchw,ndhw->ncd", x, y) / (h * w)
