"""Detection op long tail (parity: paddle/fluid/operators/detection/ and the
deformable/psroi family under operators/).

Static-shape XLA designs (same conventions as ops/detection.py): ragged
LoDTensor outputs become fixed-size padded tensors (-1 or zero padding plus
weight/mask outputs); the reference's `use_random` subsampling becomes
deterministic highest-priority sampling so programs stay replayable under jit
(documented per op).

Covered here: polygon_box_transform, yolov3_loss, psroi_pool, prroi_pool,
roi_perspective_transform, deformable_conv (v1+v2), deformable_roi_pooling,
generate_proposals, rpn_target_assign, retinanet_target_assign,
retinanet_detection_output, locality_aware_nms, distribute_fpn_proposals,
collect_fpn_proposals, box_decoder_and_assign, generate_proposal_labels,
generate_mask_labels, similarity_focus, filter_by_instag, cvm.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .detection import _iou


# -- small ones --------------------------------------------------------------


@register_op("polygon_box_transform", inputs=("Input",), outputs=("Output",),
             grad_maker=None)
def polygon_box_transform(ctx, x):
    """EAST text geo-map decode (polygon_box_transform_op.cc:38-51):
    even channels: out = 4*w_idx - in; odd: out = 4*h_idx - in."""
    N, G, H, W = x.shape
    wi = jnp.arange(W, dtype=x.dtype).reshape(1, 1, 1, W)
    hi = jnp.arange(H, dtype=x.dtype).reshape(1, 1, H, 1)
    even = (jnp.arange(G) % 2 == 0).reshape(1, G, 1, 1)
    return jnp.where(even, 4.0 * wi - x, 4.0 * hi - x)


@register_op("cvm", inputs=("X", "CVM"), outputs=("Y",),
             attrs={"use_cvm": True}, no_grad_inputs=("CVM",))
def cvm(ctx, x, cvm_in, use_cvm=True):
    """Continuous-value model op (cvm_op.h:30-40): x rows start with
    [show, click, ...]; use_cvm keeps width and rewrites the two lead
    columns to log(show+1), log(click+1)-log(show+1); else drops them."""
    if use_cvm:
        c0 = jnp.log(x[:, :1] + 1)
        c1 = jnp.log(x[:, 1:2] + 1) - c0
        return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
    return x[:, 2:]


@register_op("similarity_focus", inputs=("X",), outputs=("Out",),
             attrs={"axis": 1, "indexes": []}, grad_maker=None)
def similarity_focus(ctx, x, axis=1, indexes=()):
    """similarity_focus_op.cc: for each selected slice along `axis`, greedily
    mark per-(rest-dims) maxima: walking the sorted values of the slice, a
    cell is selected if its row and column were not yet covered; selected
    cells get 1.0 in every channel.  Vectorized equivalence: a cell (i,j) of
    the [A,B] slice is kept iff its value is the max of row i AND of col j
    after removing earlier-chosen rows/cols — the greedy fixed point equals
    iteratively pairing the global argmax; we implement the exact greedy with
    a fori_loop over min(A,B) steps."""
    if x.ndim != 4:
        raise NotImplementedError("similarity_focus expects rank-4 input")
    if axis not in (1, 2, 3):
        raise ValueError("axis must be 1, 2 or 3")
    N = x.shape[0]
    out = jnp.zeros_like(x)

    # move `axis` to position 1 -> slices [N, K, A, B]
    perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xt = jnp.transpose(x, perm)
    A, B = xt.shape[2], xt.shape[3]
    steps = min(A, B)

    def one_slice(sl):  # [A, B] -> mask [A, B]
        def body(_, carry):
            mask, rowf, colf = carry
            masked = jnp.where(rowf[:, None] | colf[None, :], -jnp.inf, sl)
            idx = jnp.argmax(masked)
            i, j = idx // B, idx % B
            ok = masked.reshape(-1)[idx] != -jnp.inf
            mask = jnp.where(ok, mask.at[i, j].set(1.0), mask)
            rowf = jnp.where(ok, rowf.at[i].set(True), rowf)
            colf = jnp.where(ok, colf.at[j].set(True), colf)
            return mask, rowf, colf

        m, _, _ = lax.fori_loop(
            0, steps, body,
            (jnp.zeros_like(sl), jnp.zeros(A, bool), jnp.zeros(B, bool)))
        return m

    sel = xt[:, jnp.asarray(list(indexes), jnp.int32)]  # [N, S, A, B]
    masks = jax.vmap(jax.vmap(one_slice))(sel)          # [N, S, A, B]
    merged = jnp.max(masks, axis=1)                     # [N, A, B]
    # broadcast selection across the focused axis
    inv = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 2, 3, 1)}[axis]
    full = jnp.broadcast_to(merged[:, None], xt.shape)
    return jnp.transpose(full, inv).astype(x.dtype)


@register_op("filter_by_instag", inputs=("Ins", "Ins_tag", "Filter_tag"),
             outputs=("Out", "LossWeight", "IndexMap"),
             attrs={"is_lod": True}, grad_maker=None)
def filter_by_instag(ctx, ins, ins_tag, filter_tag, is_lod=True):
    """filter_by_instag_op.cc, static-shape variant: instead of compacting
    matching rows (ragged), keep all rows and zero out non-matching ones;
    LossWeight is the 0/1 match mask, IndexMap maps row -> row."""
    match = jnp.isin(ins_tag.reshape(-1), filter_tag.reshape(-1))
    w = match.astype(ins.dtype)
    out = ins * w.reshape((-1,) + (1,) * (ins.ndim - 1))
    n = ins.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    return out, w.reshape(-1, 1), jnp.stack([idx, idx], axis=1)


# -- yolov3 loss --------------------------------------------------------------


def _bce(x, t):
    return jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _box_iou_cw(b1, b2):
    """IoU of center-format boxes; b1 [..., 4], b2 [..., 4]."""
    ox = jnp.minimum(b1[..., 0] + b1[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2) \
        - jnp.maximum(b1[..., 0] - b1[..., 2] / 2, b2[..., 0] - b2[..., 2] / 2)
    oy = jnp.minimum(b1[..., 1] + b1[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2) \
        - jnp.maximum(b1[..., 1] - b1[..., 3] / 2, b2[..., 1] - b2[..., 3] / 2)
    inter = jnp.where((ox < 0) | (oy < 0), 0.0, ox * oy)
    union = b1[..., 2] * b1[..., 3] + b2[..., 2] * b2[..., 3] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("yolov3_loss", inputs=("X", "GTBox", "GTLabel", "GTScore"),
             outputs=("Loss", "ObjectnessMask", "GTMatchMask"),
             attrs={"anchors": [], "anchor_mask": [], "class_num": 1,
                    "ignore_thresh": 0.7, "downsample_ratio": 32,
                    "use_label_smooth": True},
             optional_inputs=("GTScore",),
             no_grad_inputs=("GTBox", "GTLabel", "GTScore"))
def yolov3_loss(ctx, x, gt_box, gt_label, gt_score=None, anchors=(),
                anchor_mask=(), class_num=1, ignore_thresh=0.7,
                downsample_ratio=32, use_label_smooth=True):
    """YOLOv3 loss (yolov3_loss_op.h:255-420), vectorized: x
    [N, mask*(5+C), H, W]; gt_box [N, B, 4] center-normalized; outputs
    per-image Loss [N], ObjectnessMask [N, mask, H, W] (-1 ignored /
    score positive / 0 negative), GTMatchMask [N, B]."""
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(a) for a in anchor_mask]
    N, _, H, W = x.shape
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    B = gt_box.shape[1]
    input_size = downsample_ratio * H
    C = class_num

    xr = x.reshape(N, mask_num, 5 + C, H, W)
    tx, ty, tw, th, tobj = (xr[:, :, 0], xr[:, :, 1], xr[:, :, 2],
                            xr[:, :, 3], xr[:, :, 4])
    tcls = xr[:, :, 5:]  # [N, mask, C, H, W]

    if gt_score is None:
        gt_score = jnp.ones((N, B), x.dtype)
    else:
        gt_score = gt_score.reshape(N, B)

    gt_valid = (gt_box[..., 2] > 1e-6) & (gt_box[..., 3] > 1e-6)  # [N,B]

    # -- predicted boxes per cell/anchor (normalized center format)
    gi = jnp.arange(W, dtype=x.dtype).reshape(1, 1, 1, W)
    gj = jnp.arange(H, dtype=x.dtype).reshape(1, 1, H, 1)
    aw = jnp.asarray([anchors[2 * m] for m in anchor_mask],
                     x.dtype).reshape(1, mask_num, 1, 1)
    ah = jnp.asarray([anchors[2 * m + 1] for m in anchor_mask],
                     x.dtype).reshape(1, mask_num, 1, 1)
    px = (gi + jax.nn.sigmoid(tx)) / W
    py = (gj + jax.nn.sigmoid(ty)) / H
    pw = jnp.exp(tw) * aw / input_size
    ph = jnp.exp(th) * ah / input_size
    pred = jnp.stack([px, py, pw, ph], axis=-1)  # [N,mask,H,W,4]

    # best IoU of each predicted box vs any valid gt -> ignore mask
    iou_all = _box_iou_cw(pred[:, :, :, :, None, :],
                          gt_box[:, None, None, None, :, :])  # [N,m,H,W,B]
    iou_all = jnp.where(gt_valid[:, None, None, None, :], iou_all, 0.0)
    best_iou = jnp.max(iou_all, axis=-1) if B else jnp.zeros_like(px)
    obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)  # [N,m,H,W]

    # -- per-gt best anchor over the FULL anchor set (shape-only IoU)
    an_w = jnp.asarray(anchors[0::2], x.dtype) / input_size  # [A]
    an_h = jnp.asarray(anchors[1::2], x.dtype) / input_size
    shape_boxes = jnp.stack([jnp.zeros_like(an_w), jnp.zeros_like(an_w),
                             an_w, an_h], axis=-1)           # [A,4]
    gt_shift = gt_box.at[..., 0].set(0.0).at[..., 1].set(0.0)  # [N,B,4]
    iou_an = _box_iou_cw(gt_shift[:, :, None, :],
                         shape_boxes[None, None, :, :])      # [N,B,A]
    best_n = jnp.argmax(iou_an, axis=-1)                     # [N,B]
    # map anchor index -> mask slot (-1 when not in anchor_mask)
    lut = -jnp.ones((an_num,), jnp.int32)
    for slot, m in enumerate(anchor_mask):
        lut = lut.at[m].set(slot)
    match_slot = jnp.where(gt_valid, lut[best_n], -1)        # [N,B]

    g_i = jnp.clip((gt_box[..., 0] * W).astype(jnp.int32), 0, W - 1)
    g_j = jnp.clip((gt_box[..., 1] * H).astype(jnp.int32), 0, H - 1)
    pos = match_slot >= 0                                    # [N,B]
    slot_safe = jnp.maximum(match_slot, 0)

    # scatter positive-sample scores into the objectness mask
    bidx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
    obj_mask = obj_mask.at[bidx, slot_safe, g_j, g_i].set(
        jnp.where(pos, gt_score, obj_mask[bidx, slot_safe, g_j, g_i]),
        mode="drop")

    # -- objectness loss over all cells
    obj_pred = tobj  # [N,m,H,W]
    pos_l = _bce(obj_pred, 1.0) * jnp.maximum(obj_mask, 0.0)
    neg_l = jnp.where(obj_mask == 0.0, _bce(obj_pred, 0.0), 0.0)
    loss = jnp.sum(jnp.where(obj_mask > 1e-5, pos_l, neg_l), axis=(1, 2, 3))

    # -- location + class loss at matched cells (gather per gt)
    bx = gt_box[..., 0] * W - g_i.astype(x.dtype)
    by = gt_box[..., 1] * H - g_j.astype(x.dtype)
    aw_full = jnp.asarray(anchors[0::2], x.dtype)
    ah_full = jnp.asarray(anchors[1::2], x.dtype)
    bw = jnp.log(jnp.maximum(gt_box[..., 2] * input_size, 1e-9)
                 / aw_full[best_n])
    bh = jnp.log(jnp.maximum(gt_box[..., 3] * input_size, 1e-9)
                 / ah_full[best_n])
    scale = (2.0 - gt_box[..., 2] * gt_box[..., 3]) * gt_score  # [N,B]

    ptx = tx[bidx, slot_safe, g_j, g_i]
    pty = ty[bidx, slot_safe, g_j, g_i]
    ptw = tw[bidx, slot_safe, g_j, g_i]
    pth = th[bidx, slot_safe, g_j, g_i]
    loc = (_bce(ptx, bx) + _bce(pty, by)
           + jnp.abs(ptw - bw) + jnp.abs(pth - bh)) * scale
    loss = loss + jnp.sum(jnp.where(pos, loc, 0.0), axis=1)

    if use_label_smooth:
        sm = min(1.0 / C, 1.0 / 40.0)
        lab_pos, lab_neg = 1.0 - sm, sm
    else:
        lab_pos, lab_neg = 1.0, 0.0
    pcls = tcls[bidx, slot_safe, :, g_j, g_i]                # [N,B,C]
    onehot = jax.nn.one_hot(gt_label.reshape(N, B), C, dtype=x.dtype)
    tgt = onehot * lab_pos + (1 - onehot) * lab_neg
    cls_l = jnp.sum(_bce(pcls, tgt), axis=-1) * gt_score
    loss = loss + jnp.sum(jnp.where(pos, cls_l, 0.0), axis=1)

    return (loss, lax.stop_gradient(obj_mask),
            jnp.where(gt_valid, match_slot, -1).astype(jnp.int32))


# -- RoI pooling family -------------------------------------------------------


def _bilinear(img, y, x):
    """img [C,H,W]; y,x [...] continuous coords -> [C, ...] samples
    (zero outside)."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1, wx1 = y - y0, x - x0
    vals = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            ok = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yy = jnp.clip(yy, 0, H - 1)
            xx = jnp.clip(xx, 0, W - 1)
            v = img[:, yy, xx]
            vals = vals + v * (wy * wx * ok)[None]
    return vals


@register_op("psroi_pool", inputs=("X", "ROIs"), outputs=("Out",),
             attrs={"output_channels": 1, "spatial_scale": 1.0,
                    "pooled_height": 1, "pooled_width": 1},
             no_grad_inputs=("ROIs",))
def psroi_pool(ctx, x, rois, output_channels=1, spatial_scale=1.0,
               pooled_height=1, pooled_width=1):
    """Position-sensitive RoI average pooling (psroi_pool_op.h:25-140).
    rois [R, 5] = (batch_idx, x1, y1, x2, y2) — batch index in column 0
    replaces the reference's LoD row partition."""
    N, C, H, W = x.shape
    ph_, pw_ = pooled_height, pooled_width
    oc = output_channels
    assert C == oc * ph_ * pw_, "C must equal output_channels*ph*pw"

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bsh, bsw = rh / ph_, rw / pw_
        img = x[b]  # [C,H,W]
        out = jnp.zeros((oc, ph_, pw_), x.dtype)
        for phi in range(ph_):
            for pwi in range(pw_):
                hs = jnp.clip(jnp.floor(phi * bsh + y1), 0, H).astype(jnp.int32)
                he = jnp.clip(jnp.ceil((phi + 1) * bsh + y1), 0, H).astype(jnp.int32)
                ws = jnp.clip(jnp.floor(pwi * bsw + x1), 0, W).astype(jnp.int32)
                we = jnp.clip(jnp.ceil((pwi + 1) * bsw + x1), 0, W).astype(jnp.int32)
                hm = (jnp.arange(H) >= hs) & (jnp.arange(H) < he)
                wm = (jnp.arange(W) >= ws) & (jnp.arange(W) < we)
                m = hm[:, None] & wm[None, :]
                cnt = jnp.maximum(jnp.sum(m), 1)
                ch = jnp.arange(oc) * ph_ * pw_ + phi * pw_ + pwi
                plane = img[ch]  # [oc,H,W]
                s = jnp.sum(jnp.where(m[None], plane, 0.0), axis=(1, 2))
                empty = (he <= hs) | (we <= ws)
                out = out.at[:, phi, pwi].set(
                    jnp.where(empty, 0.0, s / cnt))
        return out

    return jax.vmap(one)(rois)


def _hat_integral(lo, hi, n):
    """∫_{lo}^{hi} max(0, 1-|t-p|) dt for p = 0..n-1, vectorized -> [n]."""
    p = jnp.arange(n, dtype=lo.dtype)

    def F(t):
        # antiderivative of hat centered at p, F(p-1)=0, F(p+1)=1
        u = jnp.clip(t - (p - 1.0), 0.0, 2.0)
        return jnp.where(u <= 1.0, 0.5 * u * u, 1.0 - 0.5 * (2.0 - u) ** 2)

    return F(hi) - F(lo)


@register_op("prroi_pool", inputs=("X", "ROIs"), outputs=("Out",),
             attrs={"spatial_scale": 1.0, "pooled_height": 1,
                    "pooled_width": 1})
def prroi_pool(ctx, x, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, output_channels=None):
    """Precise RoI pooling (prroi_pool_op.h, arXiv:1807.11590): the exact
    integral of the bilinearly-interpolated feature over each bin — the
    2-D integral factorizes into per-axis hat-function integrals, so each
    bin value is wy^T F wx / area.  Fully differentiable (incl. rois)."""
    N, C, H, W = x.shape
    ph_, pw_ = pooled_height, pooled_width

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1 = roi[1] * spatial_scale, roi[2] * spatial_scale
        x2, y2 = roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.0)
        rh = jnp.maximum(y2 - y1, 0.0)
        bw, bh = rw / pw_, rh / ph_
        img = x[b]

        def bin_val(phi, pwi):
            wx = _hat_integral(x1 + pwi * bw, x1 + (pwi + 1) * bw, W)
            wy = _hat_integral(y1 + phi * bh, y1 + (phi + 1) * bh, H)
            area = jnp.maximum(bw * bh, 1e-9)
            return jnp.einsum("h,chw,w->c", wy, img, wx) / area

        rows = [jnp.stack([bin_val(i, j) for j in range(pw_)], -1)
                for i in range(ph_)]
        return jnp.stack(rows, -2)  # [C, ph, pw]

    return jax.vmap(one)(rois)


@register_op("roi_perspective_transform", inputs=("X", "ROIs"),
             outputs=("Out", "Mask", "TransformMatrix",
                      "Out2InIdx", "Out2InWeights"),
             attrs={"transformed_height": 1, "transformed_width": 1,
                    "spatial_scale": 1.0},
             no_grad_inputs=("ROIs",))
def roi_perspective_transform(ctx, x, rois, transformed_height=1,
                              transformed_width=1, spatial_scale=1.0):
    """Perspective-warp quadrilateral rois to a fixed grid
    (roi_perspective_transform_op.cc): rois [R, 9] = (batch_idx, 8 corner
    coords x1..y4 clockwise from top-left); output [R, C, th, tw]."""
    N, C, H, W = x.shape
    th_, tw_ = transformed_height, transformed_width

    def transform_matrix(q):
        # q: 8 coords scaled; solve the homography mapping the output grid
        # corners (0,0),(tw-1,0),(tw-1,th-1),(0,th-1) to the quad
        x1, y1, x2, y2, x3, y3, x4, y4 = [q[i] for i in range(8)]
        dst = jnp.asarray([[0.0, 0.0], [tw_ - 1.0, 0.0],
                           [tw_ - 1.0, th_ - 1.0], [0.0, th_ - 1.0]],
                          q.dtype)
        src = jnp.stack([jnp.stack([x1, y1]), jnp.stack([x2, y2]),
                         jnp.stack([x3, y3]), jnp.stack([x4, y4])])
        rows = []
        rhs = []
        for k in range(4):
            X, Y = dst[k, 0], dst[k, 1]
            u, v = src[k, 0], src[k, 1]
            rows.append(jnp.stack([X, Y, jnp.ones_like(X),
                                   jnp.zeros_like(X), jnp.zeros_like(X),
                                   jnp.zeros_like(X), -X * u, -Y * u]))
            rhs.append(u)
            rows.append(jnp.stack([jnp.zeros_like(X), jnp.zeros_like(X),
                                   jnp.zeros_like(X), X, Y,
                                   jnp.ones_like(X), -X * v, -Y * v]))
            rhs.append(v)
        A = jnp.stack(rows)
        bv = jnp.stack(rhs)
        h = jnp.linalg.solve(A, bv)
        return jnp.concatenate([h, jnp.ones((1,), q.dtype)])

    def one(roi):
        b = roi[0].astype(jnp.int32)
        quad = roi[1:] * spatial_scale
        hmat = transform_matrix(quad)
        Hm = hmat.reshape(3, 3)
        gy, gx = jnp.meshgrid(jnp.arange(th_, dtype=x.dtype),
                              jnp.arange(tw_, dtype=x.dtype), indexing="ij")
        ones = jnp.ones_like(gx)
        pts = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
        mapped = Hm @ pts
        u = mapped[0] / mapped[2]
        v = mapped[1] / mapped[2]
        inside = (u >= -0.5) & (u < W - 0.5) & (v >= -0.5) & (v < H - 0.5)
        samples = _bilinear(x[b], v, u)  # [C, th*tw]
        out = (samples * inside[None]).reshape(C, th_, tw_)
        return out, inside.reshape(th_, tw_).astype(jnp.int32), hmat

    outs, masks, mats = jax.vmap(one)(rois)
    R = rois.shape[0]
    dummy_idx = jnp.zeros((R, 4), jnp.int32)
    dummy_w = jnp.zeros((R, 4), x.dtype)
    return outs, masks[:, None], mats, dummy_idx, dummy_w


# -- deformable ---------------------------------------------------------------


@register_op("deformable_conv", inputs=("Input", "Offset", "Mask", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "deformable_groups": 1, "im2col_step": 64})
def deformable_conv(ctx, x, offset, mask, w, strides=(1, 1), paddings=(0, 0),
                    dilations=(1, 1), groups=1, deformable_groups=1,
                    im2col_step=64):
    """Modulated deformable conv v2 (deformable_conv_op.h; arXiv:1811.11168).
    x [N,C,H,W]; offset [N, 2*dg*kh*kw, OH, OW] (y,x interleaved per kernel
    point, reference layout); mask [N, dg*kh*kw, OH, OW]; w [O, C/g, kh, kw].
    Implemented as bilinear gather -> grouped einsum (im2col_step is a CUDA
    tiling knob — XLA handles tiling)."""
    return _deform_conv_impl(x, offset, mask, w, strides, paddings,
                             dilations, groups, deformable_groups)


@register_op("deformable_conv_v1", inputs=("Input", "Offset", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "deformable_groups": 1, "im2col_step": 64})
def deformable_conv_v1(ctx, x, offset, w, strides=(1, 1), paddings=(0, 0),
                       dilations=(1, 1), groups=1, deformable_groups=1,
                       im2col_step=64):
    return _deform_conv_impl(x, offset, None, w, strides, paddings,
                             dilations, groups, deformable_groups)


def _deform_conv_impl(x, offset, mask, w, strides, paddings, dilations,
                      groups, dg):
    N, C, H, W = x.shape
    O, _, kh, kw = w.shape
    sh, sw = int(strides[0]), int(strides[1])
    ph, pw = int(paddings[0]), int(paddings[1])
    dh, dw = int(dilations[0]), int(dilations[1])
    OH = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    OW = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    K = kh * kw

    oy, ox = jnp.meshgrid(jnp.arange(OH, dtype=x.dtype),
                          jnp.arange(OW, dtype=x.dtype), indexing="ij")
    ky, kx = jnp.meshgrid(jnp.arange(kh, dtype=x.dtype),
                          jnp.arange(kw, dtype=x.dtype), indexing="ij")
    base_y = oy[None] * sh - ph + ky.reshape(K, 1, 1) * dh  # [K,OH,OW]
    base_x = ox[None] * sw - pw + kx.reshape(K, 1, 1) * dw

    off = offset.reshape(N, dg, K, 2, OH, OW)
    samp_y = base_y[None, None] + off[:, :, :, 0]  # [N,dg,K,OH,OW]
    samp_x = base_x[None, None] + off[:, :, :, 1]
    if mask is not None:
        mk = mask.reshape(N, dg, K, OH, OW)
    else:
        mk = jnp.ones((N, dg, K, OH, OW), x.dtype)

    cg = C // dg  # channels per deformable group

    def per_image(img, sy, sx, m):
        # img [C,H,W]; sy/sx/m [dg,K,OH,OW]
        def per_dg(ch_img, dy, dx, dm):
            # ch_img [cg,H,W]
            v = _bilinear(ch_img, dy.reshape(-1), dx.reshape(-1))
            v = v.reshape(cg, K, OH, OW) * dm[None]
            return v

        cols = jax.vmap(per_dg)(img.reshape(dg, cg, H, W), sy, sx, m)
        return cols.reshape(C, K, OH, OW)

    cols = jax.vmap(per_image)(x, samp_y, samp_x, mk)  # [N,C,K,OH,OW]

    cpg = C // groups
    opg = O // groups
    cols_g = cols.reshape(N, groups, cpg, K, OH, OW)
    w_g = w.reshape(groups, opg, cpg, K)
    out = jnp.einsum("ngckhw,gock->ngohw", cols_g, w_g)
    return out.reshape(N, O, OH, OW)


@register_op("deformable_psroi_pooling",
             inputs=("Input", "ROIs", "Trans"),
             outputs=("Output", "TopCount"),
             attrs={"no_trans": False, "spatial_scale": 1.0,
                    "output_dim": 1, "group_size": [1], "pooled_height": 1,
                    "pooled_width": 1, "part_size": [1], "sample_per_part": 4,
                    "trans_std": 0.1},
             optional_inputs=("Trans",), no_grad_inputs=("ROIs",))
def deformable_psroi_pooling(ctx, x, rois, trans=None, no_trans=False,
                             spatial_scale=1.0, output_dim=1, group_size=(1,),
                             pooled_height=1, pooled_width=1, part_size=(1,),
                             sample_per_part=4, trans_std=0.1):
    """Deformable PS-RoI pooling (deformable_psroi_pooling_op.h): bins are
    shifted by learned normalized offsets then average-pooled with
    sample_per_part bilinear samples per axis."""
    N, C, H, W = x.shape
    ph_, pw_ = pooled_height, pooled_width
    if isinstance(group_size, (list, tuple)):
        gh_n = int(group_size[0])
        gw_n = int(group_size[1]) if len(group_size) > 1 else gh_n
    else:
        gh_n = gw_n = int(group_size)
    psz = part_size[0] if isinstance(part_size, (list, tuple)) else part_size
    sp = sample_per_part
    od = output_dim

    def one(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - 0.5
        y1 = roi[2] * spatial_scale - 0.5
        x2 = (roi[3] + 1.0) * spatial_scale - 0.5
        y2 = (roi[4] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ph_, rw / pw_
        sub_h, sub_w = bh / sp, bw / sp
        img = x[b]
        out = jnp.zeros((od, ph_, pw_), x.dtype)
        cnt = jnp.zeros((od, ph_, pw_), x.dtype)
        for phi in range(ph_):
            for pwi in range(pw_):
                if no_trans or trans is None:
                    off_y = jnp.zeros(())
                    off_x = jnp.zeros(())
                else:
                    part_h = int(phi * psz / ph_)
                    part_w = int(pwi * psz / pw_)
                    off_y = tr[0, part_h, part_w] * trans_std * rh
                    off_x = tr[1, part_h, part_w] * trans_std * rw
                ys = y1 + phi * bh + off_y
                xs = x1 + pwi * bw + off_x
                sy = ys + (jnp.arange(sp, dtype=x.dtype) + 0.5) * sub_h
                sx = xs + (jnp.arange(sp, dtype=x.dtype) + 0.5) * sub_w
                gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
                gch = jnp.arange(od)
                # position-sensitive channel: c = (ctop*gh_n + gh)*gw_n + gw
                gh_idx = min(int(phi * gh_n / ph_), gh_n - 1)
                gw_idx = min(int(pwi * gw_n / pw_), gw_n - 1)
                ch = (gch * gh_n + gh_idx) * gw_n + gw_idx
                v = _bilinear(img[ch], gy.reshape(-1), gx.reshape(-1))
                ok = ((gy.reshape(-1) >= -0.5) & (gy.reshape(-1) < H - 0.5)
                      & (gx.reshape(-1) >= -0.5) & (gx.reshape(-1) < W - 0.5))
                s = jnp.sum(v * ok[None], axis=1)
                c = jnp.maximum(jnp.sum(ok), 1).astype(x.dtype)
                out = out.at[:, phi, pwi].set(s / c)
                cnt = cnt.at[:, phi, pwi].set(c)
        return out, cnt

    if trans is None or no_trans:
        tr_in = jnp.zeros((rois.shape[0], 2, 1, 1), x.dtype)
    else:
        tr_in = trans
    outs, cnts = jax.vmap(one)(rois, tr_in)
    return outs, lax.stop_gradient(cnts)


# -- proposal generation / target assignment ---------------------------------


def _decode_anchor(anchor, var, delta):
    """bbox_util: anchors [A,4] corner fmt, deltas [A,4] -> decoded corners."""
    aw = anchor[:, 2] - anchor[:, 0] + 1.0
    ah = anchor[:, 3] - anchor[:, 1] + 1.0
    acx = anchor[:, 0] + 0.5 * aw
    acy = anchor[:, 1] + 0.5 * ah
    dx, dy, dw, dh = (delta[:, 0] * var[:, 0], delta[:, 1] * var[:, 1],
                      delta[:, 2] * var[:, 2], delta[:, 3] * var[:, 3])
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(jnp.minimum(dw, 10.0)) * aw
    h = jnp.exp(jnp.minimum(dh, 10.0)) * ah
    return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                      cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=1)


def _encode_anchor(anchor, gt, var=None):
    aw = anchor[:, 2] - anchor[:, 0] + 1.0
    ah = anchor[:, 3] - anchor[:, 1] + 1.0
    acx = anchor[:, 0] + 0.5 * aw
    acy = anchor[:, 1] + 0.5 * ah
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * gw
    gcy = gt[:, 1] + 0.5 * gh
    t = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                   jnp.log(jnp.maximum(gw / aw, 1e-9)),
                   jnp.log(jnp.maximum(gh / ah, 1e-9))], axis=1)
    if var is not None:
        t = t / var
    return t


_iou_off = _iou  # shared helper (ops/detection.py) — offset param covers both


def _nms_keep(boxes, scores, thresh, max_keep, iou_offset=0.0):
    """Greedy NMS over a fixed candidate set ordered by score desc.
    Returns keep mask [M] with at most max_keep kept."""
    M = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_off(b, b, iou_offset)

    def body(i, keep):
        sup = jnp.sum(jnp.where(jnp.arange(M) < i, (iou[i] > thresh) & keep,
                                False)) > 0
        return keep.at[i].set(~sup & keep[i])

    keep0 = scores[order] > -jnp.inf
    keep = lax.fori_loop(0, M, body, keep0)
    # cap at max_keep
    rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    keep = keep & (rank < max_keep)
    inv = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M))
    return keep[inv]


@register_op("generate_proposals",
             inputs=("Scores", "BboxDeltas", "ImInfo", "Anchors",
                     "Variances"),
             outputs=("RpnRois", "RpnRoiProbs", "RpnRoisNum"),
             attrs={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                    "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0},
             grad_maker=None)
def generate_proposals(ctx, scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_topN=6000, post_nms_topN=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0):
    """RPN proposal generation (generate_proposals_op.cc): decode -> clip ->
    filter small -> topk -> NMS.  Fixed-size output [N*post_nms_topN, 5]
    (batch_idx, x1, y1, x2, y2) zero-padded; RpnRoisNum [N] gives valid
    counts (replaces the reference's LoD)."""
    N = scores.shape[0]
    A4 = anchors.reshape(-1, 4)
    V4 = variances.reshape(-1, 4)
    M = A4.shape[0]
    pre_n = min(pre_nms_topN, M)
    post_n = min(post_nms_topN, pre_n)

    def per_image(sc, bd, info):
        s = sc.transpose(1, 2, 0).reshape(-1)            # [M] anchor-major
        d = bd.transpose(1, 2, 0).reshape(-1, 4)
        props = _decode_anchor(A4, V4, d)
        hgt, wdt = info[0], info[1]
        props = jnp.stack([
            jnp.clip(props[:, 0], 0.0, wdt - 1.0),
            jnp.clip(props[:, 1], 0.0, hgt - 1.0),
            jnp.clip(props[:, 2], 0.0, wdt - 1.0),
            jnp.clip(props[:, 3], 0.0, hgt - 1.0)], axis=1)
        ms = min_size * info[2]
        keep_sz = ((props[:, 2] - props[:, 0] + 1.0 >= ms)
                   & (props[:, 3] - props[:, 1] + 1.0 >= ms))
        s = jnp.where(keep_sz, s, -jnp.inf)
        top_s, top_i = lax.top_k(s, pre_n)
        top_b = props[top_i]
        keep = _nms_keep(top_b, top_s, nms_thresh, post_n)
        keep = keep & (top_s > -jnp.inf)
        # compact kept entries to the front (stable by score order)
        order = jnp.argsort(~keep)  # kept first, already score-sorted
        kb = top_b[order][:post_n]
        ks = top_s[order][:post_n]
        km = keep[order][:post_n]
        return (jnp.where(km[:, None], kb, 0.0),
                jnp.where(km, ks, 0.0), jnp.sum(km.astype(jnp.int32)))

    rois, probs, nums = jax.vmap(per_image)(scores, bbox_deltas, im_info)
    bidx = jnp.repeat(jnp.arange(N, dtype=rois.dtype), post_n).reshape(
        N, post_n, 1)
    rois5 = jnp.concatenate([bidx, rois], axis=-1).reshape(-1, 5)
    return rois5, probs.reshape(-1, 1), nums


@register_op("rpn_target_assign",
             inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo"),
             outputs=("LocationIndex", "ScoreIndex", "TargetLabel",
                      "TargetBBox", "BBoxInsideWeight"),
             attrs={"rpn_batch_size_per_im": 256, "rpn_straddle_thresh": 0.0,
                    "rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3,
                    "rpn_fg_fraction": 0.5, "use_random": True},
             grad_maker=None)
def rpn_target_assign(ctx, anchor, gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      rpn_fg_fraction=0.5, use_random=True):
    """RPN anchor sampling (rpn_target_assign_op.cc).  Static-shape design:
    gt_boxes [N, G, 4] padded (zero-area rows invalid; replaces LoD),
    is_crowd [N, G].  Outputs are fixed-size per batch: fg slots
    F = batch*fg_fraction, total slots S = batch size per im; padded slots
    carry index 0 with zero BBoxInsideWeight / label 0.  `use_random`
    subsampling is deterministic highest-IoU-first (replayable under jit)."""
    N, G, _ = gt_boxes.shape
    A = anchor.shape[0]
    S = rpn_batch_size_per_im
    F = int(S * rpn_fg_fraction)
    dt = anchor.dtype

    def per_image(gts, crowd, info):
        valid_gt = ((gts[:, 2] - gts[:, 0]) > 0) & ((gts[:, 3] - gts[:, 1]) > 0)
        valid_gt = valid_gt & (crowd == 0)
        inside = jnp.ones((A,), bool)
        if rpn_straddle_thresh >= 0:
            hgt, wdt = info[0], info[1]
            st = rpn_straddle_thresh
            inside = ((anchor[:, 0] >= -st) & (anchor[:, 1] >= -st)
                      & (anchor[:, 2] < wdt + st) & (anchor[:, 3] < hgt + st))
        iou = _iou(anchor, gts)                        # [A,G]
        iou = jnp.where(valid_gt[None, :] & inside[:, None], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)              # [A]
        best_iou = jnp.max(iou, axis=1)
        # (i) best anchor per gt is fg
        best_anchor_iou = jnp.max(iou, axis=0)         # [G]
        is_best = jnp.any(
            (iou == best_anchor_iou[None, :]) & (best_anchor_iou[None, :] > 0)
            & valid_gt[None, :], axis=1)
        fg = (best_iou >= rpn_positive_overlap) | is_best
        fg = fg & inside
        # an image with no valid gt (best_iou stays -1) still yields
        # backgrounds — every inside anchor is negative
        bg = (~fg) & inside & (best_iou < rpn_negative_overlap)
        # deterministic sampling: fg by IoU desc, bg by IoU desc; pad the
        # candidate axis so top_k(k) is valid when A < slots
        pad_n = max(S, F) - A if max(S, F) > A else 0
        pad = jnp.full((pad_n,), -jnp.inf, dt)
        fg_score = jnp.concatenate(
            [jnp.where(fg, best_iou + 2.0, -jnp.inf), pad])
        fg_val, fg_idx = lax.top_k(fg_score, F)
        n_fg = jnp.minimum(jnp.sum(fg.astype(jnp.int32)), F)
        fg_ok = fg_val > -jnp.inf
        n_bg_want = S - n_fg
        bg_score = jnp.concatenate(
            [jnp.where(bg, best_iou + 1.0, -jnp.inf), pad])
        bg_val, bg_idx = lax.top_k(bg_score, S)
        bg_ok = (bg_val > -jnp.inf) & (jnp.arange(S) < n_bg_want)
        loc_idx = jnp.where(fg_ok, fg_idx, 0)
        tbox = _encode_anchor(anchor[loc_idx], gts[best_gt[loc_idx]])
        tbox = jnp.where(fg_ok[:, None], tbox, 0.0)
        inw = jnp.where(fg_ok[:, None], jnp.ones((F, 4), dt), 0.0)
        score_idx = jnp.concatenate([
            jnp.where(fg_ok, fg_idx, 0),
            jnp.where(bg_ok, bg_idx, 0)])
        # padded slots carry label -100 — the DEFAULT ignore_index of
        # fluid.layers.sigmoid_cross_entropy_with_logits — so reference-style
        # loss code drops them without extra arguments (the reference
        # returns ragged sampled-only indices instead)
        labels = jnp.concatenate([
            jnp.where(fg_ok, 1, -100).astype(jnp.int32),
            jnp.where(bg_ok, 0, -100).astype(jnp.int32)])
        return loc_idx, score_idx, labels, tbox, inw

    li, si, lab, tb, iw = jax.vmap(per_image)(gt_boxes, is_crowd, im_info)
    # offset indices per image into the flattened [N*A] anchor axis
    off = (jnp.arange(N, dtype=jnp.int32) * A)[:, None]
    return ((li + off).reshape(-1, 1), (si + off).reshape(-1, 1),
            lab.reshape(-1, 1), tb.reshape(-1, 4), iw.reshape(-1, 4))


@register_op("retinanet_target_assign",
             inputs=("Anchor", "GtBoxes", "GtLabels", "IsCrowd", "ImInfo"),
             outputs=("LocationIndex", "ScoreIndex", "TargetLabel",
                      "TargetBBox", "BBoxInsideWeight", "ForegroundNumber"),
             attrs={"positive_overlap": 0.5, "negative_overlap": 0.4},
             grad_maker=None)
def retinanet_target_assign(ctx, anchor, gt_boxes, gt_labels, is_crowd,
                            im_info, positive_overlap=0.5,
                            negative_overlap=0.4):
    """RetinaNet target assign (detection.py:65-288): every non-ignored
    anchor is used (no subsampling); fg label = gt class, bg label = 0.
    Static-shape: all N*A anchors appear in ScoreIndex; ignored anchors
    (neg<iou<pos) carry label -1 which the focal-loss path masks out."""
    N, G, _ = gt_boxes.shape
    A = anchor.shape[0]
    dt = anchor.dtype

    def per_image(gts, glab, crowd):
        valid_gt = ((gts[:, 2] - gts[:, 0]) > 0) & ((gts[:, 3] - gts[:, 1]) > 0)
        valid_gt = valid_gt & (crowd == 0)
        iou = jnp.where(valid_gt[None, :], _iou(anchor, gts), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        best_anchor_iou = jnp.max(iou, axis=0)
        is_best = jnp.any(
            (iou == best_anchor_iou[None, :]) & (best_anchor_iou[None, :] > 0)
            & valid_gt[None, :], axis=1)
        fg = (best_iou >= positive_overlap) | is_best
        bg = (~fg) & (best_iou < negative_overlap) & (best_iou >= 0)
        label = jnp.where(fg, glab[best_gt].astype(jnp.int32),
                          jnp.where(bg, 0, -1))
        tbox = _encode_anchor(anchor, gts[best_gt])
        tbox = jnp.where(fg[:, None], tbox, 0.0)
        inw = jnp.where(fg[:, None], jnp.ones((A, 4), dt), 0.0)
        return label, tbox, inw, jnp.sum(fg.astype(jnp.int32))

    lab, tb, iw, nfg = jax.vmap(per_image)(
        gt_boxes, gt_labels.reshape(N, G), is_crowd)
    idx = (jnp.arange(N * A, dtype=jnp.int32)).reshape(-1, 1)
    return (idx, idx, lab.reshape(-1, 1), tb.reshape(-1, 4),
            iw.reshape(-1, 4), jnp.maximum(nfg, 1).reshape(N, 1))


@register_op("generate_proposal_labels",
             inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes", "ImInfo"),
             outputs=("Rois", "LabelsInt32", "BboxTargets",
                      "BboxInsideWeights", "BboxOutsideWeights"),
             attrs={"batch_size_per_im": 256, "fg_fraction": 0.25,
                    "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
                    "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2],
                    "class_nums": 81, "use_random": True,
                    "is_cls_agnostic": False, "is_cascade_rcnn": False},
             grad_maker=None)
def generate_proposal_labels(ctx, rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Fast-RCNN RoI sampling (generate_proposal_labels_op.cc).  Static
    design: rpn_rois [N, R, 4] per image (from generate_proposals reshaped),
    gt_* [N, G, .] padded.  Output fixed [N*batch_size_per_im, .] with
    deterministic IoU-priority sampling; BboxTargets are per-class expanded
    ([S, 4*class_nums]) as the reference does."""
    N, R, _ = rpn_rois.shape
    G = gt_boxes.shape[1]
    S = batch_size_per_im
    F = int(S * fg_fraction)
    dt = rpn_rois.dtype
    wts = jnp.asarray(bbox_reg_weights, dt)

    def per_image(rois, gcls, crowd, gts):
        valid_gt = ((gts[:, 2] - gts[:, 0]) > 0) & ((gts[:, 3] - gts[:, 1]) > 0)
        not_crowd = valid_gt & (crowd == 0)
        # gt boxes join the candidate set (reference concatenates them)
        cand = jnp.concatenate([rois, gts], axis=0)      # [R+G,4]
        valid_cand = jnp.concatenate([
            (rois[:, 2] - rois[:, 0]) > 0, not_crowd])
        iou = jnp.where(not_crowd[None, :], _iou(cand, gts), -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        fg = valid_cand & (best_iou >= fg_thresh)
        bg = valid_cand & (best_iou < bg_thresh_hi) & (
            best_iou >= bg_thresh_lo)
        pad_n = max(S, F) - (R + G) if max(S, F) > (R + G) else 0
        pad = jnp.full((pad_n,), -jnp.inf, dt)
        fg_val, fg_idx = lax.top_k(
            jnp.concatenate([jnp.where(fg, best_iou, -jnp.inf), pad]), F)
        fg_ok = fg_val > -jnp.inf
        bg_val, bg_idx = lax.top_k(
            jnp.concatenate([jnp.where(bg, best_iou, -jnp.inf), pad]), S)
        bg_has = bg_val > -jnp.inf
        # compact: valid fg slots first, then bg fill, then take S — so
        # backgrounds backfill unclaimed fg quota (n_fg < F keeps the RoI
        # batch full, matching the reference's S-n_fg background count)
        prio = jnp.concatenate([
            jnp.where(fg_ok, 0, 2), jnp.where(bg_has, 1, 2)])
        order = jnp.argsort(prio, stable=True)[:S]
        all_idx = jnp.concatenate([fg_idx, bg_idx])
        all_fg = jnp.concatenate([fg_ok, jnp.zeros((S,), bool)])
        all_ok = jnp.concatenate([fg_ok, bg_has])
        sel = jnp.where(all_ok[order], all_idx[order], 0)
        sel_fg = all_fg[order]
        sel_ok = all_ok[order]
        out_rois = jnp.where(sel_ok[:, None], cand[sel], 0.0)
        lbl = jnp.where(sel_fg, gcls[best_gt[sel]].astype(jnp.int32), 0)
        tgt = _encode_anchor(cand[sel], gts[best_gt[sel]], wts[None, :])
        tgt = jnp.where(sel_fg[:, None], tgt, 0.0)
        # per-class expansion
        ncls = 2 if is_cls_agnostic else class_nums
        cls_slot = jnp.where(sel_fg, 1 if is_cls_agnostic else lbl, 0)
        bt = jnp.zeros((S, 4 * ncls), dt)
        col = cls_slot[:, None] * 4 + jnp.arange(4)[None, :]
        bt = jax.vmap(lambda row, c, v: row.at[c].set(v))(bt, col, tgt)
        iw = jnp.zeros((S, 4 * ncls), dt)
        iw = jax.vmap(lambda row, c, v: row.at[c].set(v))(
            iw, col, jnp.where(sel_fg[:, None], 1.0, 0.0) * jnp.ones((S, 4), dt))
        return out_rois, lbl, bt, iw, iw

    ro, lb, bt, iw, ow = jax.vmap(per_image)(
        rpn_rois, gt_classes.reshape(N, G), is_crowd.reshape(N, G), gt_boxes)
    return (ro.reshape(-1, 4), lb.reshape(-1, 1),
            bt.reshape(N * S, -1), iw.reshape(N * S, -1),
            ow.reshape(N * S, -1))


def _rasterize_polys(polys, lens, box, M):
    """Host rasterizer: even-odd point-in-polygon on an MxM grid over `box`.
    polys: [P, 2] flattened vertex list; lens: [n_poly] vertex counts."""
    x1, y1, x2, y2 = box
    # sample bin centers (half-pixel offsets), COCO-style
    xs = x1 + (x2 - x1) * (np.arange(M) + 0.5) / M
    ys = y1 + (y2 - y1) * (np.arange(M) + 0.5) / M
    gx, gy = np.meshgrid(xs, ys)
    mask = np.zeros((M, M), bool)
    start = 0
    for ln in lens:
        ln = int(ln)
        if ln < 3:
            start += ln
            continue
        v = polys[start:start + ln]
        start += ln
        inside = np.zeros((M, M), bool)
        j = ln - 1
        for i in range(ln):
            xi, yi = v[i]
            xj, yj = v[j]
            cond = ((yi > gy) != (yj > gy)) & (
                gx < (xj - xi) * (gy - yi) / (yj - yi + 1e-12) + xi)
            inside ^= cond
            j = i
        mask |= inside
    return mask.astype(np.float32)


@register_op("generate_mask_labels",
             inputs=("ImInfo", "GtClasses", "IsCrowd", "GtSegms", "Rois",
                     "LabelsInt32"),
             outputs=("MaskRois", "RoiHasMaskInt32", "MaskInt32"),
             attrs={"num_classes": 81, "resolution": 14},
             grad_maker=None)
def generate_mask_labels(ctx, im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes=81, resolution=14):
    """Mask-RCNN mask targets (generate_mask_labels_op.cc).  Static design:
    gt_segms [N, G, P, 2] padded polygon (single polygon per gt, padded
    vertices repeat the last point); rois [N, S, 4]; fg rois (label>0) get a
    rasterized class-slotted mask, others -1.  Rasterization runs on host
    via pure_callback (CPU-only op in the reference too)."""
    N, S, _ = rois.shape
    G, P = gt_segms.shape[1], gt_segms.shape[2]
    M = resolution

    def host(rois_h, labels_h, segms_h, classes_h, crowd_h):
        NS = rois_h.shape[0] * rois_h.shape[1]
        out = -np.ones((rois_h.shape[0], rois_h.shape[1],
                        num_classes * M * M), np.int32)
        for n in range(rois_h.shape[0]):
            # greedily match each fg roi to the gt with max IoU
            for s in range(rois_h.shape[1]):
                lab = int(labels_h[n, s])
                if lab <= 0:
                    continue
                roi = rois_h[n, s]
                best, best_g = 0.0, -1
                for g in range(segms_h.shape[1]):
                    if crowd_h[n, g] or int(classes_h[n, g]) != lab:
                        continue
                    poly = segms_h[n, g]
                    px1, py1 = poly[:, 0].min(), poly[:, 1].min()
                    px2, py2 = poly[:, 0].max(), poly[:, 1].max()
                    ix1, iy1 = max(roi[0], px1), max(roi[1], py1)
                    ix2, iy2 = min(roi[2], px2), min(roi[3], py2)
                    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
                    a1 = (roi[2] - roi[0]) * (roi[3] - roi[1])
                    a2 = (px2 - px1) * (py2 - py1)
                    iou = inter / max(a1 + a2 - inter, 1e-9)
                    if iou > best:
                        best, best_g = iou, g
                if best_g < 0:
                    continue
                m = _rasterize_polys(segms_h[n, best_g],
                                     [segms_h.shape[2]], roi, M)
                full = np.zeros((num_classes, M, M), np.int32)
                full[lab] = m.astype(np.int32)
                out[n, s] = full.reshape(-1)
        return out

    mask = jax.pure_callback(
        host,
        jax.ShapeDtypeStruct((N, S, num_classes * M * M), jnp.int32),
        rois, labels_int32.reshape(N, S), gt_segms,
        gt_classes.reshape(N, G), is_crowd.reshape(N, G))
    has = (labels_int32.reshape(N, S) > 0).astype(jnp.int32)
    bidx = jnp.repeat(jnp.arange(N, dtype=rois.dtype), S).reshape(N, S, 1)
    rois5 = jnp.concatenate([bidx, rois], axis=-1)
    return (rois5.reshape(-1, 5), has.reshape(-1, 1),
            mask.reshape(N * S, -1))


# -- FPN / output-stage ops ---------------------------------------------------


@register_op("distribute_fpn_proposals", inputs=("FpnRois",),
             outputs=("MultiFpnRois", "RestoreIndex"),
             attrs={"min_level": 2, "max_level": 5, "refer_level": 4,
                    "refer_scale": 224},
             duplicable_outputs=("MultiFpnRois",), grad_maker=None)
def distribute_fpn_proposals(ctx, fpn_rois, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224):
    """distribute_fpn_proposals_op.cc: route each roi to pyramid level
    floor(refer+log2(sqrt(area)/scale)).  Static design: each level output
    keeps the full [R, 4] shape with non-member rows zeroed (a row's level
    is recoverable from RestoreIndex ordering in the reference; here masks
    do that job)."""
    rois = fpn_rois[:, -4:]
    R = rois.shape[0]
    area = jnp.maximum((rois[:, 2] - rois[:, 0] + 1.0)
                       * (rois[:, 3] - rois[:, 1] + 1.0), 1e-12)
    lvl = jnp.floor(refer_level + jnp.log2(jnp.sqrt(area) / refer_scale + 1e-12))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs = []
    for L in range(min_level, max_level + 1):
        m = (lvl == L)
        outs.append(jnp.where(m[:, None], rois, 0.0))
    restore = jnp.argsort(jnp.argsort(lvl, stable=True), stable=True)
    return outs, restore.reshape(-1, 1).astype(jnp.int32)


@register_op("collect_fpn_proposals",
             inputs=("MultiLevelRois", "MultiLevelScores"),
             outputs=("FpnRois",),
             attrs={"post_nms_topN": -1},
             duplicable_inputs=("MultiLevelRois", "MultiLevelScores"),
             grad_maker=None)
def collect_fpn_proposals(ctx, rois_list, scores_list, post_nms_topN=-1):
    """collect_fpn_proposals_op.cc: concat levels, take global top-k by
    score.  Fixed output [post_nms_topN, 4] zero-padded."""
    if not isinstance(rois_list, (list, tuple)):
        rois_list, scores_list = [rois_list], [scores_list]
    rois = jnp.concatenate([r[:, -4:] for r in rois_list], axis=0)
    scores = jnp.concatenate([s.reshape(-1) for s in scores_list], axis=0)
    k = post_nms_topN if post_nms_topN > 0 else scores.shape[0]
    k = min(k, scores.shape[0])
    top_s, top_i = lax.top_k(scores, k)
    return rois[top_i]


@register_op("box_decoder_and_assign",
             inputs=("PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"),
             outputs=("DecodeBox", "OutputAssignBox"),
             attrs={"box_clip": 4.135},
             grad_maker=None)
def box_decoder_and_assign(ctx, prior_box, prior_box_var, target_box,
                           box_score, box_clip=4.135):
    """box_decoder_and_assign_op.cc: decode per-class deltas against priors,
    then pick each prior's best-scoring class box."""
    R = prior_box.shape[0]
    C = box_score.shape[1]
    pw = prior_box[:, 2] - prior_box[:, 0] + 1.0
    ph = prior_box[:, 3] - prior_box[:, 1] + 1.0
    pcx = prior_box[:, 0] + 0.5 * pw
    pcy = prior_box[:, 1] + 0.5 * ph
    t = target_box.reshape(R, C, 4)
    var = prior_box_var.reshape(R, 1, 4)
    dx = t[..., 0] * var[..., 0]
    dy = t[..., 1] * var[..., 1]
    dw = jnp.clip(t[..., 2] * var[..., 2], -box_clip, box_clip)
    dh = jnp.clip(t[..., 3] * var[..., 3], -box_clip, box_clip)
    cx = dx * pw[:, None] + pcx[:, None]
    cy = dy * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                     cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=-1)
    best = jnp.argmax(box_score, axis=1)
    assign = dec[jnp.arange(R), best]
    return dec.reshape(R, C * 4), assign


@register_op("retinanet_detection_output",
             inputs=("BBoxes", "Scores", "Anchors", "ImInfo"),
             outputs=("Out", "OutNum"),
             attrs={"score_threshold": 0.05, "nms_top_k": 1000,
                    "keep_top_k": 100, "nms_threshold": 0.3, "nms_eta": 1.0},
             duplicable_inputs=("BBoxes", "Scores", "Anchors"),
             grad_maker=None)
def retinanet_detection_output(ctx, bboxes_list, scores_list, anchors_list,
                               im_info, score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3, nms_eta=1.0):
    """retinanet_detection_output_op.cc: per-level decode + threshold, then
    class-wise NMS, keep top keep_top_k.  Fixed output [N*keep_top_k, 6]
    (label, score, x1, y1, x2, y2), -1-padded; OutNum [N]."""
    if not isinstance(bboxes_list, (list, tuple)):
        bboxes_list = [bboxes_list]
        scores_list = [scores_list]
        anchors_list = [anchors_list]
    N = bboxes_list[0].shape[0]
    C = scores_list[0].shape[-1]

    def per_image(args):
        deltas_l, scores_l, info = args
        all_boxes, all_scores, all_cls = [], [], []
        for deltas, sc, anc in zip(deltas_l, scores_l, anchors_list):
            A = anc.reshape(-1, 4)
            var = jnp.full_like(A, 1.0)
            dec = _decode_anchor(A, var, deltas.reshape(-1, 4))
            hgt, wdt = info[0] / info[2], info[1] / info[2]
            dec = jnp.stack([
                jnp.clip(dec[:, 0], 0.0, wdt - 1.0),
                jnp.clip(dec[:, 1], 0.0, hgt - 1.0),
                jnp.clip(dec[:, 2], 0.0, wdt - 1.0),
                jnp.clip(dec[:, 3], 0.0, hgt - 1.0)], axis=1)
            s = sc.reshape(-1, C)
            # per-level top nms_top_k by best class score
            k = min(nms_top_k, s.shape[0])
            best = jnp.max(s, axis=1)
            _, ti = lax.top_k(best, k)
            all_boxes.append(dec[ti])
            all_scores.append(s[ti])
        boxes = jnp.concatenate(all_boxes, 0)     # [M,4]
        scores = jnp.concatenate(all_scores, 0)   # [M,C]
        M = boxes.shape[0]
        outs = []
        for c in range(1, C):  # 0 is background
            sc = jnp.where(scores[:, c] > score_threshold, scores[:, c],
                           -jnp.inf)
            keep = _nms_keep(boxes, sc, nms_threshold, keep_top_k)
            keep = keep & (sc > -jnp.inf)
            outs.append((jnp.full((M,), float(c)), sc, keep))
        labs = jnp.concatenate([o[0] for o in outs])
        scs = jnp.concatenate([o[1] for o in outs])
        kps = jnp.concatenate([o[2] for o in outs])
        bxs = jnp.concatenate([boxes] * (C - 1), 0)
        scs = jnp.where(kps, scs, -jnp.inf)
        k = keep_top_k
        top_s, top_i = lax.top_k(scs, k)
        ok = top_s > -jnp.inf
        det = jnp.concatenate([
            jnp.where(ok, labs[top_i], -1.0)[:, None],
            jnp.where(ok, top_s, -1.0)[:, None],
            jnp.where(ok[:, None], bxs[top_i], -1.0)], axis=1)
        return det, jnp.sum(ok.astype(jnp.int32))

    # one trace for the whole batch: the per-level lists form a vmappable
    # pytree (program size stays O(1) in N)
    dets, nums = jax.vmap(per_image)(
        (list(bboxes_list), list(scores_list), im_info))
    return dets.reshape(-1, 6), nums


@register_op("locality_aware_nms", inputs=("BBoxes", "Scores"),
             outputs=("Out",),
             attrs={"background_label": -1, "score_threshold": 0.0,
                    "nms_top_k": -1, "nms_threshold": 0.3, "nms_eta": 1.0,
                    "keep_top_k": 100, "normalized": True},
             grad_maker=None)
def locality_aware_nms(ctx, bboxes, scores, background_label=-1,
                       score_threshold=0.0, nms_top_k=-1, nms_threshold=0.3,
                       nms_eta=1.0, keep_top_k=100, normalized=True):
    """locality_aware_nms_op.cc (EAST): first weighted-merge consecutive
    overlapping boxes (score-weighted average of coordinates), then standard
    NMS capped at nms_top_k.  bboxes [N, M, 4]; scores [N, 1, M].  Output
    [N*keep_top_k, 6] -1-padded.  normalized=False applies the +1
    pixel-coordinate IoU convention."""
    N, M, _ = bboxes.shape
    off = 0.0 if normalized else 1.0

    def per_image(boxes, sc):
        sc = sc.reshape(-1)
        # locality merge: walk boxes in order; merge row-adjacent overlaps
        def body(i, carry):
            mb, ms, cnt = carry  # merged boxes/scores, count of merged slots
            cur_b, cur_s = boxes[i], sc[i]
            prev = jnp.maximum(cnt - 1, 0)
            iou = _iou_off(cur_b[None], mb[prev][None], off)[0, 0]
            do_merge = (cnt > 0) & (iou > nms_threshold)
            wsum = ms[prev] + cur_s
            merged = (mb[prev] * ms[prev] + cur_b * cur_s) / jnp.maximum(
                wsum, 1e-12)
            mb = jnp.where(do_merge, mb.at[prev].set(merged),
                           mb.at[cnt].set(cur_b))
            ms = jnp.where(do_merge, ms.at[prev].set(wsum),
                           ms.at[cnt].set(cur_s))
            cnt = jnp.where(do_merge, cnt, cnt + 1)
            return mb, ms, cnt

        mb0 = jnp.zeros_like(boxes)
        ms0 = jnp.full((M,), -jnp.inf, sc.dtype)
        mb, ms, cnt = lax.fori_loop(0, M, body, (mb0, ms0, 0))
        ms = jnp.where(jnp.arange(M) < cnt, ms, -jnp.inf)
        ms = jnp.where(ms > score_threshold, ms, -jnp.inf)
        if nms_top_k > 0 and nms_top_k < M:
            # pre-truncate to the top nms_top_k candidates before NMS
            kth = lax.top_k(ms, nms_top_k)[0][-1]
            ms = jnp.where(ms >= kth, ms, -jnp.inf)
        keep = _nms_keep(mb, ms, nms_threshold, keep_top_k, iou_offset=off)
        keep = keep & (ms > -jnp.inf)
        k = keep_top_k
        sckeep = jnp.where(keep, ms, -jnp.inf)
        top_s, top_i = lax.top_k(sckeep, min(k, M))
        ok = top_s > -jnp.inf
        det = jnp.concatenate([
            jnp.where(ok, 0.0, -1.0)[:, None],
            jnp.where(ok, top_s, -1.0)[:, None],
            jnp.where(ok[:, None], mb[top_i], -1.0)], axis=1)
        if det.shape[0] < k:
            det = jnp.concatenate([
                det, -jnp.ones((k - det.shape[0], 6), det.dtype)])
        return det

    return jax.vmap(per_image)(bboxes, scores).reshape(-1, 6)
