"""Collective ops — ICI mesh collectives replacing NCCL.

Parity: paddle/fluid/operators/collective/ (c_allreduce_op.h:58-105,
c_broadcast_op, c_allgather_op, c_reducescatter_op, c_comm_init_op,
c_gen_nccl_id_op).  Where the reference calls ncclAllReduce on a communicator
looked up by ring_id, these lower to jax.lax collectives over a named mesh
axis when the block runs inside shard_map (manual SPMD); on a single device
or under auto-SPMD sharding propagation they are identity (XLA inserts the
collectives itself).  ring_id maps to a mesh axis name via LowerCtx.

Stream-ordering ops (c_sync_calc_stream / c_sync_comm_stream) are no-ops:
XLA owns scheduling.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _axis_for_ring(ctx, ring_id):
    if ctx is None or not ctx.axis_names:
        return None
    names = ctx.axis_names
    axis = names[int(ring_id) % len(names)]
    # size-1 axis: every collective is the identity — lower to a no-op
    # instead of emitting degenerate psum/all_gather HLO.  ~160 such
    # per-gradient allreduces acted as fusion barriers and cost the
    # single-chip shard_map path ~8-17% vs the plain executor (round-3
    # profiling); a real pod axis (>1) is unaffected.
    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and mesh.shape.get(axis, 0) == 1:
        return None
    return axis


def _register_allreduce(name, op):
    @register_op(
        "c_allreduce_" + name,
        inputs=("X",),
        outputs=("Out",),
        attrs={"ring_id": 0, "use_calc_stream": False, "use_model_parallel": False},
        grad_maker=None,
    )
    def _low(ctx, x, ring_id=0, _op=op, **_):
        axis = _axis_for_ring(ctx, ring_id)
        if axis is None:
            return x
        return _op(x, axis)

    return _low


def _pprod(x, axis):
    # exact cross-rank product (sign/zero-safe): gather then reduce
    gathered = lax.all_gather(x, axis)  # [nranks, ...]
    return jnp.prod(gathered, axis=0)


@register_op("c_allreduce_sum", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "scale": 1.0, "use_calc_stream": False,
                    "use_model_parallel": False},
             grad_maker=None)
def c_allreduce_sum(ctx, x, ring_id=0, scale=1.0, **_):
    """psum with the gradient-averaging scale folded in (post-reduce
    multiply), so the transpilers stop emitting a standalone per-gradient
    scale op.  scale=1.0 is a plain sum.

    FLAGS_deterministic_reduction replaces psum with all_gather + a
    fixed-order pairwise tree reduce: psum's reduction order is the
    backend's choice (ring segments, rank topology), so the same shards
    can sum to different bits on different launches/world sizes — the
    dp-sharded reduction-reassociation term in the dp4_tp2 parity gap.
    The tree below is a pure function of nranks, so the grad sum is
    bit-reproducible across launches (and matches any other consumer of
    the same tree).  Costs gather bandwidth (n*|x| vs the ring's 2*|x|);
    a debug/parity tool, not the fast path."""
    axis = _axis_for_ring(ctx, ring_id)
    if axis is not None:
        from .. import flags as _flags

        if _flags.flag("deterministic_reduction"):
            gathered = lax.all_gather(x, axis)  # [nranks, ...]
            terms = [gathered[i] for i in range(gathered.shape[0])]
            # fixed-order pairwise tree: adjacent pairs each level, odd
            # tail promoted unchanged.  Static python loop — the order is
            # baked into the HLO, identical on every rank and launch.
            while len(terms) > 1:
                nxt = [terms[i] + terms[i + 1]
                       for i in range(0, len(terms) - 1, 2)]
                if len(terms) % 2:
                    nxt.append(terms[-1])
                terms = nxt
            x = terms[0]
        else:
            x = lax.psum(x, axis)
    if scale != 1.0:
        x = x * jnp.asarray(scale, x.dtype)
    return x


_register_allreduce("max", lambda x, a: lax.pmax(x, a))
_register_allreduce("min", lambda x, a: lax.pmin(x, a))
_register_allreduce("prod", _pprod)


@register_op("c_broadcast", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "root": 0, "use_calc_stream": False},
             grad_maker=None)
def c_broadcast(ctx, x, ring_id=0, root=0, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return x
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def _allgather_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    n = max(int(op.attr("nranks") or 1), 1)
    if x.shape:
        shp = list(x.shape)
        shp[0] = shp[0] * n
        out.shape = tuple(shp)
    if out.dtype is None:
        out.dtype = x.dtype


def _dim0_split_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    n = max(int(op.attr("nranks") or 1), 1)
    if x.shape:
        shp = list(x.shape)
        shp[0] = shp[0] // n
        out.shape = tuple(shp)
    if out.dtype is None:
        out.dtype = x.dtype


@register_op("c_allgather", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "use_calc_stream": False},
             grad_maker=None, infer_shape=_allgather_infer)
def c_allgather(ctx, x, ring_id=0, nranks=1, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        n = max(int(nranks), 1)
        # degenerate world: keep the declared [n*d0, ...] shape
        return jnp.concatenate([x] * n, axis=0) if n > 1 else x
    return lax.all_gather(x, axis, tiled=True)


@register_op("c_reducescatter", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "scale": 1.0,
                    "use_calc_stream": False},
             grad_maker=None, infer_shape=_dim0_split_infer)
def c_reducescatter(ctx, x, ring_id=0, nranks=1, scale=1.0, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        n = max(int(nranks), 1)
        # degenerate world: rank-0 chunk, keeping the declared shard shape
        out = lax.slice_in_dim(x, 0, x.shape[0] // n, axis=0) if n > 1 else x
    else:
        out = lax.psum_scatter(x, axis, tiled=True)
    if scale != 1.0:
        out = out * jnp.asarray(scale, out.dtype)
    return out


@register_op("c_shard_slice", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1}, grad_maker=None,
             infer_shape=_dim0_split_infer)
def c_shard_slice(ctx, x, ring_id=0, nranks=1, **_):
    """This rank's 1/nranks dim-0 block of a replicated tensor (the ZeRO-1
    param shard feeding the shard-local optimizer update).  Purely local —
    nothing crosses the wire — but axis_index makes it mesh-dependent."""
    n = max(int(nranks), 1)
    if n <= 1:
        return x
    shard = x.shape[0] // n
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return lax.slice_in_dim(x, 0, shard, axis=0)
    return lax.dynamic_slice_in_dim(x, lax.axis_index(axis) * shard, shard,
                                    axis=0)


# ---------------------------------------------------------------------------
# Quantized gradient exchange (EQuARX-style, FLAGS_allreduce_dtype=bf16|int8):
# c_quant_pack chunks a gradient into nranks rank-aligned rows of
# bucket-padded payload with one f32 max-abs scale per (rank, bucket), then
# c_allreduce_qsum / c_reducescatter_q move only the narrow payload + scales
# over the wire (all_to_all, dequant-accumulate in f32, and — for the
# allreduce form — requantize before the all-gather phase so both wire
# phases stay narrow: int8 lands at ~0.25x the f32 ring-allreduce bytes).

_QMAX = 127.0


def _ceil_div(a, b):
    return -(-a // b)


def _pack_chunks(x, nranks, bucket):
    """[*orig] -> [nranks, nb, bucket] f32.  Chunk boundaries sit at
    ceil(S/nranks) elements so row r holds exactly the elements destined
    for rank r (for a ZeRO-1 grad with dim0 % nranks == 0, row r IS the
    dim-0 shard r); bucket padding is per-chunk trailing zeros."""
    flat = x.astype(jnp.float32).reshape(-1)
    s = flat.shape[0]
    n = max(int(nranks), 1)
    b = int(bucket)
    chunk = _ceil_div(s, n)
    if chunk * n != s:
        flat = jnp.pad(flat, (0, chunk * n - s))
    g = flat.reshape(n, chunk)
    nb = _ceil_div(chunk, b)
    if nb * b != chunk:
        g = jnp.pad(g, ((0, 0), (0, nb * b - chunk)))
    return g.reshape(n, nb, b)


def _quantize(g, dtype):
    """[..., bucket] f32 -> (payload, [...] f32 scales)."""
    if dtype == "bf16":
        return g.astype(jnp.bfloat16), jnp.ones(g.shape[:-1], jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(g), axis=-1) / _QMAX,
                        jnp.float32(1e-30))
    q = jnp.clip(jnp.round(g / scale[..., None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def _wire_dtype(dtype):
    return "bfloat16" if dtype == "bf16" else "int8"


def _quant_pack_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    sc = block.var(op.output("Scale")[0])
    n = max(int(op.attr("nranks") or 1), 1)
    b = int(op.attr("bucket") or 512)
    s = 1
    for d in (x.shape or ()):
        s *= d
    nb = _ceil_div(_ceil_div(s, n), b)
    out.shape = (n, nb, b)
    out.dtype = _wire_dtype(op.attr("dtype"))
    sc.shape = (n, nb)
    sc.dtype = "float32"


@register_op("c_quant_pack", inputs=("X",), outputs=("Out", "Scale"),
             attrs={"ring_id": 0, "nranks": 1, "bucket": 512,
                    "dtype": "int8"},
             grad_maker=None, infer_shape=_quant_pack_infer)
def c_quant_pack(ctx, x, ring_id=0, nranks=1, bucket=512, dtype="int8", **_):
    g = _pack_chunks(x, nranks, bucket)
    return _quantize(g, dtype)


def _a2a_dequant_shard(ctx, q, scale, ring_id):
    """all_to_all payload+scales, dequant, f32-accumulate this rank's
    chunk: [n, nb, bucket] -> [nb, bucket].  axis-None (degenerate world)
    keeps the rank-0-chunk convention of c_shard_slice."""
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return q[0].astype(jnp.float32) * scale[0][..., None]
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                           tiled=True)
    return jnp.sum(q.astype(jnp.float32) * scale[..., None], axis=0)


def _qsum_infer(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attr("orig_shape"))
    if out.dtype is None:
        out.dtype = "float32"


def _rs_q_infer(op, block):
    out = block.var(op.output("Out")[0])
    orig = tuple(op.attr("orig_shape"))
    n = max(int(op.attr("nranks") or 1), 1)
    out.shape = (orig[0] // n,) + orig[1:]
    if out.dtype is None:
        out.dtype = "float32"


@register_op("c_allreduce_qsum", inputs=("X", "Scale"), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "bucket": 512,
                    "dtype": "int8", "scale": 1.0, "orig_shape": []},
             grad_maker=None, infer_shape=_qsum_infer)
def c_allreduce_qsum(ctx, q, qscale, ring_id=0, nranks=1, bucket=512,
                     dtype="int8", scale=1.0, orig_shape=(), **_):
    """Quantized sum-allreduce of the tensor c_quant_pack packed into (X,
    Scale).  Out is the full f32 result (replicated-path form)."""
    n = max(int(nranks), 1)
    orig = tuple(orig_shape)
    s = 1
    for d in orig:
        s *= d
    chunk = _ceil_div(s, n)
    shard = _a2a_dequant_shard(ctx, q, qscale, ring_id)  # [nb, bucket]
    if scale != 1.0:
        shard = shard * jnp.float32(scale)
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        full = jnp.concatenate([shard[None]] * n, axis=0)  # degenerate
    else:
        # requantize the accumulated chunk so the gather phase is as
        # narrow as the scatter phase
        q2, s2 = _quantize(shard, dtype)
        g2 = lax.all_gather(q2, axis, tiled=True)       # [n*nb, bucket]
        sc2 = lax.all_gather(s2, axis, tiled=True)      # [n*nb]
        full = (g2.astype(jnp.float32) * sc2[:, None]).reshape(n, -1)
    flat = full.reshape(n, -1)[:, :chunk].reshape(-1)
    return flat[:s].reshape(orig)


@register_op("c_reducescatter_q", inputs=("X", "Scale"), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "bucket": 512,
                    "dtype": "int8", "scale": 1.0, "orig_shape": []},
             grad_maker=None, infer_shape=_rs_q_infer)
def c_reducescatter_q(ctx, q, qscale, ring_id=0, nranks=1, bucket=512,
                      dtype="int8", scale=1.0, orig_shape=(), **_):
    """Quantized reduce-scatter: this rank's dim-0 shard of the f32 sum
    (the ZeRO-1 gradient exchange).  Requires orig dim0 % nranks == 0 so
    the chunk is exactly the shard — the transpiler guarantees it."""
    n = max(int(nranks), 1)
    orig = tuple(orig_shape)
    chunk = 1
    for d in (orig[0] // n,) + orig[1:]:
        chunk *= d
    shard = _a2a_dequant_shard(ctx, q, qscale, ring_id)  # [nb, bucket]
    if scale != 1.0:
        shard = shard * jnp.float32(scale)
    return shard.reshape(-1)[:chunk].reshape((orig[0] // n,) + orig[1:])


def _allgather_q_infer(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attr("orig_shape"))
    if out.dtype is None:
        out.dtype = "float32"


@register_op("c_allgather_q", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "bucket": 512,
                    "dtype": "int8", "orig_shape": []},
             grad_maker=None, infer_shape=_allgather_q_infer)
def c_allgather_q(ctx, x, ring_id=0, nranks=1, bucket=512, dtype="int8",
                  orig_shape=(), **_):
    """Quantized weight all-gather (ZeRO-1 param reassembly): each rank
    bucket-quantizes its own updated f32 shard, gathers the narrow payload
    + scales, dequantizes — then splices its OWN exact f32 shard back over
    its block.  The master shard (what c_shard_slice hands the optimizer
    next step) therefore never accumulates quantization error; only the
    local replicas of OTHER ranks' blocks are lossy."""
    n = max(int(nranks), 1)
    orig = tuple(orig_shape)
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None or n <= 1:
        # degenerate world: replicate the exact shard, keep declared shape
        return jnp.concatenate([x] * n, axis=0) if n > 1 else x
    s = 1
    for d in x.shape:
        s *= d
    b = max(1, min(int(bucket), s))
    nb = _ceil_div(s, b)
    flat = x.astype(jnp.float32).reshape(-1)
    if nb * b != s:
        flat = jnp.pad(flat, (0, nb * b - s))
    q, sc = _quantize(flat.reshape(nb, b), dtype)
    g = lax.all_gather(q, axis, tiled=True)          # [n*nb, b]
    gs = lax.all_gather(sc, axis, tiled=True)        # [n*nb]
    full = (g.astype(jnp.float32) * gs[:, None]).reshape(n, -1)
    full = full[:, :s].reshape(orig)
    shard_d0 = orig[0] // n
    return lax.dynamic_update_slice_in_dim(
        full, x.astype(jnp.float32), lax.axis_index(axis) * shard_d0, axis=0)


@register_op("c_sync_calc_stream", inputs=("X",), outputs=("Out",),
             grad_maker=None)
def c_sync_calc_stream(ctx, x):
    return x  # XLA ordering makes stream syncs structural no-ops


@register_op("c_sync_comm_stream", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0}, grad_maker=None,
             duplicable_inputs=("X",), duplicable_outputs=("Out",))
def c_sync_comm_stream(ctx, xs, ring_id=0):
    # tuple-wrap: the duplicable-output convention (a bare 1-element list
    # would be mistaken for a positional slot tuple by run_op)
    return (list(xs),)


@register_op("c_gen_nccl_id", inputs=(), outputs=("Out",),
             attrs={"rank": 0, "endpoint": "", "other_endpoints": [],
                    "ring_id": 0}, grad_maker=None)
def c_gen_nccl_id(ctx, rank=0, endpoint="", other_endpoints=(), ring_id=0):
    """Communicator bootstrap is structural on TPU (the mesh IS the
    communicator); emit a placeholder id so the program stays runnable."""
    return jnp.zeros((1,), jnp.int32)


@register_op("c_comm_init", inputs=("X",), outputs=(),
             attrs={"nranks": 1, "rank": 0, "ring_id": 0, "device_id": -1},
             grad_maker=None, optional_inputs=("X",))
def c_comm_init(ctx, x, **_):
    return ()


@register_op("c_comm_init_all", inputs=(), outputs=(),
             attrs={"devices": [], "ring_id": 0}, grad_maker=None)
def c_comm_init_all(ctx, devices=(), ring_id=0):
    return ()


# legacy transpiler-era bootstrap op (distributed_ops/gen_nccl_id_op.cc)
@register_op("gen_nccl_id", inputs=(), outputs=("NCCLID",),
             attrs={"trainers": [], "trainer_id": 0,
                    "nccl_comm_num": 1, "use_hierarchical_allreduce": False,
                    "hierarchical_allreduce_inter_nranks": 1},
             grad_maker=None)
def gen_nccl_id(ctx, **_):
    return jnp.zeros((1,), jnp.int32)


@register_op("allreduce", inputs=("X",), outputs=("Out",),
             attrs={"reduce_type": 0}, grad_maker=None)
def allreduce(ctx, x, reduce_type=0):
    """Dygraph-mode allreduce; reduce_type enum matches the reference
    (allreduce_op.h RedType): 0=sum, 1=max, 2=min, 3=prod."""
    axis = _axis_for_ring(ctx, 0)
    if axis is None:
        return x
    fns = [lax.psum, lax.pmax, lax.pmin, _pprod]
    return fns[int(reduce_type)](x, axis)


@register_op("broadcast", inputs=("X",), outputs=("Out",),
             attrs={"root": 0, "sync_mode": False}, grad_maker=None)
def broadcast(ctx, x, root=0, sync_mode=False):
    return c_broadcast(ctx, x, root=root)


@register_op("listen_and_serv", inputs=("X",), outputs=(),
             attrs={"endpoint": "", "Fanin": 1}, grad_maker=None,
             optional_inputs=("X",))
def listen_and_serv(ctx, x=None, endpoint="", Fanin=1):
    """Pserver event-loop op (listen_and_serv_op.cc:110).  Never lowered:
    the executor intercepts programs carrying _ps_server metadata and runs
    the blocking server loop (distributed/ps.py) instead."""
    raise RuntimeError(
        "listen_and_serv cannot be lowered to XLA; run the pserver program "
        "through Executor.run (it blocks in the PS server loop)")


@register_op("distributed_lookup_table", inputs=("Ids", "W"),
             outputs=("Out",),
             attrs={"ring_id": 0, "table_size": 0, "padding_idx": -1})
def distributed_lookup_table(ctx, ids, w, ring_id=0, table_size=0,
                             padding_idx=-1):
    """Row-sharded embedding lookup (TPU-native analog of
    distributed_lookup_table_op.cc + parameter_prefetch.cc: ids routed to
    the pserver owning each row-section; here each mesh rank owns a
    contiguous row block and contributes masked partial gathers summed over
    ICI).

    Inside shard_map: `w` is the LOCAL shard [V/n, D]; rank r owns global
    rows [r*V/n, (r+1)*V/n).  Outside a mesh: plain gather (w is the full
    table).  Fully differentiable — the vjp scatter-adds into the local
    shard and the psum transposes to identity."""
    idx = ids.reshape(-1)
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        out = w[idx]
    else:
        vlocal = w.shape[0]
        rank = lax.axis_index(axis)
        offset = rank * vlocal
        local = idx - offset
        valid = (local >= 0) & (local < vlocal)
        safe = jnp.clip(local, 0, vlocal - 1)
        part = jnp.where(valid[:, None], w[safe], 0.0)
        out = lax.psum(part, axis)
    if padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[:, None], 0.0, out)
    return out.reshape(ids.shape[:-1] + (w.shape[-1],)) if (
        ids.ndim > 1 and ids.shape[-1] == 1) else out.reshape(
        ids.shape + (w.shape[-1],))


@register_op("moe_ffn", inputs=("X", "GateW", "W1", "B1", "W2", "B2"),
             outputs=("Out", "AuxLoss"),
             attrs={"top_k": 2, "capacity_factor": 1.25, "ring_id": -1,
                    "axis_name": ""})
def moe_ffn_op(ctx, x, gate_w, w1, b1, w2, b2, top_k=2,
               capacity_factor=1.25, ring_id=-1, axis_name=""):
    """Mixture-of-experts FFN (parallel/moe.py).  Expert parallelism over a
    mesh axis selected by `axis_name` (string) or `ring_id` >= 0 (index);
    otherwise all experts are local (single device / auto-SPMD)."""
    from ..parallel.moe import moe_ffn as _moe

    if axis_name and ctx is not None and axis_name in (ctx.axis_names or ()):
        axis = axis_name
    elif ring_id >= 0:
        axis = _axis_for_ring(ctx, ring_id)
    else:
        axis = None
    shp = x.shape
    flat = x.reshape(-1, shp[-1])
    out, aux = _moe(flat, gate_w, w1, b1, w2, b2, top_k=top_k,
                    capacity_factor=capacity_factor, axis_name=axis)
    return out.reshape(shp), aux
