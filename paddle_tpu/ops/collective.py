"""Collective ops — ICI mesh collectives replacing NCCL.

Parity: paddle/fluid/operators/collective/ (c_allreduce_op.h:58-105,
c_broadcast_op, c_allgather_op, c_reducescatter_op, c_comm_init_op,
c_gen_nccl_id_op).  Where the reference calls ncclAllReduce on a communicator
looked up by ring_id, these lower to jax.lax collectives over a named mesh
axis when the block runs inside shard_map (manual SPMD); on a single device
or under auto-SPMD sharding propagation they are identity (XLA inserts the
collectives itself).  ring_id maps to a mesh axis name via LowerCtx.

Stream-ordering ops (c_sync_calc_stream / c_sync_comm_stream) are no-ops:
XLA owns scheduling.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _axis_for_ring(ctx, ring_id):
    if ctx is None or not ctx.axis_names:
        return None
    names = ctx.axis_names
    axis = names[int(ring_id) % len(names)]
    # size-1 axis: every collective is the identity — lower to a no-op
    # instead of emitting degenerate psum/all_gather HLO.  ~160 such
    # per-gradient allreduces acted as fusion barriers and cost the
    # single-chip shard_map path ~8-17% vs the plain executor (round-3
    # profiling); a real pod axis (>1) is unaffected.
    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and mesh.shape.get(axis, 0) == 1:
        return None
    return axis


def _register_allreduce(name, op):
    @register_op(
        "c_allreduce_" + name,
        inputs=("X",),
        outputs=("Out",),
        attrs={"ring_id": 0, "use_calc_stream": False, "use_model_parallel": False},
        grad_maker=None,
    )
    def _low(ctx, x, ring_id=0, _op=op, **_):
        axis = _axis_for_ring(ctx, ring_id)
        if axis is None:
            return x
        return _op(x, axis)

    return _low


def _pprod(x, axis):
    # exact cross-rank product (sign/zero-safe): gather then reduce
    gathered = lax.all_gather(x, axis)  # [nranks, ...]
    return jnp.prod(gathered, axis=0)


_register_allreduce("sum", lambda x, a: lax.psum(x, a))
_register_allreduce("max", lambda x, a: lax.pmax(x, a))
_register_allreduce("min", lambda x, a: lax.pmin(x, a))
_register_allreduce("prod", _pprod)


@register_op("c_broadcast", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "root": 0, "use_calc_stream": False},
             grad_maker=None)
def c_broadcast(ctx, x, ring_id=0, root=0, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return x
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


@register_op("c_allgather", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "use_calc_stream": False},
             grad_maker=None)
def c_allgather(ctx, x, ring_id=0, nranks=1, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return x
    return lax.all_gather(x, axis, tiled=True)


@register_op("c_reducescatter", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1, "use_calc_stream": False},
             grad_maker=None)
def c_reducescatter(ctx, x, ring_id=0, nranks=1, **_):
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        return x
    return lax.psum_scatter(x, axis, tiled=True)


@register_op("c_sync_calc_stream", inputs=("X",), outputs=("Out",),
             grad_maker=None)
def c_sync_calc_stream(ctx, x):
    return x  # XLA ordering makes stream syncs structural no-ops


@register_op("c_sync_comm_stream", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0}, grad_maker=None,
             duplicable_inputs=("X",), duplicable_outputs=("Out",))
def c_sync_comm_stream(ctx, xs, ring_id=0):
    # tuple-wrap: the duplicable-output convention (a bare 1-element list
    # would be mistaken for a positional slot tuple by run_op)
    return (list(xs),)


@register_op("c_gen_nccl_id", inputs=(), outputs=("Out",),
             attrs={"rank": 0, "endpoint": "", "other_endpoints": [],
                    "ring_id": 0}, grad_maker=None)
def c_gen_nccl_id(ctx, rank=0, endpoint="", other_endpoints=(), ring_id=0):
    """Communicator bootstrap is structural on TPU (the mesh IS the
    communicator); emit a placeholder id so the program stays runnable."""
    return jnp.zeros((1,), jnp.int32)


@register_op("c_comm_init", inputs=("X",), outputs=(),
             attrs={"nranks": 1, "rank": 0, "ring_id": 0, "device_id": -1},
             grad_maker=None, optional_inputs=("X",))
def c_comm_init(ctx, x, **_):
    return ()


@register_op("c_comm_init_all", inputs=(), outputs=(),
             attrs={"devices": [], "ring_id": 0}, grad_maker=None)
def c_comm_init_all(ctx, devices=(), ring_id=0):
    return ()


# legacy transpiler-era bootstrap op (distributed_ops/gen_nccl_id_op.cc)
@register_op("gen_nccl_id", inputs=(), outputs=("NCCLID",),
             attrs={"trainers": [], "trainer_id": 0,
                    "nccl_comm_num": 1, "use_hierarchical_allreduce": False,
                    "hierarchical_allreduce_inter_nranks": 1},
             grad_maker=None)
def gen_nccl_id(ctx, **_):
    return jnp.zeros((1,), jnp.int32)


@register_op("allreduce", inputs=("X",), outputs=("Out",),
             attrs={"reduce_type": 0}, grad_maker=None)
def allreduce(ctx, x, reduce_type=0):
    """Dygraph-mode allreduce; reduce_type enum matches the reference
    (allreduce_op.h RedType): 0=sum, 1=max, 2=min, 3=prod."""
    axis = _axis_for_ring(ctx, 0)
    if axis is None:
        return x
    fns = [lax.psum, lax.pmax, lax.pmin, _pprod]
    return fns[int(reduce_type)](x, axis)


@register_op("broadcast", inputs=("X",), outputs=("Out",),
             attrs={"root": 0, "sync_mode": False}, grad_maker=None)
def broadcast(ctx, x, root=0, sync_mode=False):
    return c_broadcast(ctx, x, root=root)


@register_op("listen_and_serv", inputs=("X",), outputs=(),
             attrs={"endpoint": "", "Fanin": 1}, grad_maker=None,
             optional_inputs=("X",))
def listen_and_serv(ctx, x=None, endpoint="", Fanin=1):
    """Pserver event-loop op (listen_and_serv_op.cc:110).  Never lowered:
    the executor intercepts programs carrying _ps_server metadata and runs
    the blocking server loop (distributed/ps.py) instead."""
    raise RuntimeError(
        "listen_and_serv cannot be lowered to XLA; run the pserver program "
        "through Executor.run (it blocks in the PS server loop)")


@register_op("distributed_lookup_table", inputs=("Ids", "W"),
             outputs=("Out",),
             attrs={"ring_id": 0, "table_size": 0, "padding_idx": -1})
def distributed_lookup_table(ctx, ids, w, ring_id=0, table_size=0,
                             padding_idx=-1):
    """Row-sharded embedding lookup (TPU-native analog of
    distributed_lookup_table_op.cc + parameter_prefetch.cc: ids routed to
    the pserver owning each row-section; here each mesh rank owns a
    contiguous row block and contributes masked partial gathers summed over
    ICI).

    Inside shard_map: `w` is the LOCAL shard [V/n, D]; rank r owns global
    rows [r*V/n, (r+1)*V/n).  Outside a mesh: plain gather (w is the full
    table).  Fully differentiable — the vjp scatter-adds into the local
    shard and the psum transposes to identity."""
    idx = ids.reshape(-1)
    axis = _axis_for_ring(ctx, ring_id)
    if axis is None:
        out = w[idx]
    else:
        vlocal = w.shape[0]
        rank = lax.axis_index(axis)
        offset = rank * vlocal
        local = idx - offset
        valid = (local >= 0) & (local < vlocal)
        safe = jnp.clip(local, 0, vlocal - 1)
        part = jnp.where(valid[:, None], w[safe], 0.0)
        out = lax.psum(part, axis)
    if padding_idx >= 0:
        out = jnp.where((idx == padding_idx)[:, None], 0.0, out)
    return out.reshape(ids.shape[:-1] + (w.shape[-1],)) if (
        ids.ndim > 1 and ids.shape[-1] == 1) else out.reshape(
        ids.shape + (w.shape[-1],))


@register_op("moe_ffn", inputs=("X", "GateW", "W1", "B1", "W2", "B2"),
             outputs=("Out", "AuxLoss"),
             attrs={"top_k": 2, "capacity_factor": 1.25, "ring_id": -1,
                    "axis_name": ""})
def moe_ffn_op(ctx, x, gate_w, w1, b1, w2, b2, top_k=2,
               capacity_factor=1.25, ring_id=-1, axis_name=""):
    """Mixture-of-experts FFN (parallel/moe.py).  Expert parallelism over a
    mesh axis selected by `axis_name` (string) or `ring_id` >= 0 (index);
    otherwise all experts are local (single device / auto-SPMD)."""
    from ..parallel.moe import moe_ffn as _moe

    if axis_name and ctx is not None and axis_name in (ctx.axis_names or ()):
        axis = axis_name
    elif ring_id >= 0:
        axis = _axis_for_ring(ctx, ring_id)
    else:
        axis = None
    shp = x.shape
    flat = x.reshape(-1, shp[-1])
    out, aux = _moe(flat, gate_w, w1, b1, w2, b2, top_k=top_k,
                    capacity_factor=capacity_factor, axis_name=axis)
    return out.reshape(shp), aux
