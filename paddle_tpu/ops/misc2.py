"""Misc tensor ops rounding out the layer API surface.

Parity (paddle/fluid/operators/): multiplex_op.cc, crop_op.cc /
crop_tensor_op.cc, pad_constant_like_op.cc, scatter_nd_add_op.cc,
shard_index_op.cc, sampling_id_op.cc, random_crop_op.cc, unique_op.cc /
unique_with_counts_op.cc (padded static-shape variant), gather_tree_op.cc,
add_position_encoding_op.cc, selu_op.cc, activation_op.cc (soft_relu).
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("multiplex", inputs=("Ids", "X"), outputs=("Out",),
             duplicable_inputs=("X",), no_grad_inputs=("Ids",))
def multiplex(ctx, ids, xs):
    """Row-wise select among candidate tensors (multiplex_op.cc)."""
    stacked = jnp.stack(xs, axis=0)          # [K, N, ...]
    idx = ids.reshape(-1).astype(jnp.int32)  # [N]
    return stacked[idx, jnp.arange(stacked.shape[1])]


@register_op("crop_tensor", inputs=("X",), outputs=("Out",),
             attrs={"offsets": [], "shape": []})
def crop_tensor(ctx, x, offsets=(), shape=()):
    offs = list(offsets) or [0] * x.ndim
    shp = [x.shape[i] - offs[i] if s in (-1, 0) else s
           for i, s in enumerate(shape or list(x.shape))]
    return lax.slice(x, offs, [o + s for o, s in zip(offs, shp)])


@register_op("crop", inputs=("X", "Y"), outputs=("Out",),
             attrs={"offsets": [], "shape": []},
             optional_inputs=("Y",), no_grad_inputs=("Y",))
def crop(ctx, x, y=None, offsets=(), shape=()):
    shp = list(y.shape) if y is not None else list(shape)
    return crop_tensor(ctx, x, offsets=offsets, shape=shp)


@register_op("pad_constant_like", inputs=("X", "Y"), outputs=("Out",),
             attrs={"pad_value": 0.0}, no_grad_inputs=("X",))
def pad_constant_like(ctx, x, y, pad_value=0.0):
    """Pad y up to x's shape (pad_constant_like_op.cc)."""
    pads = [(0, xd - yd) for xd, yd in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


@register_op("scatter_nd", inputs=("Index", "Updates", "Shape"),
             outputs=("Out",), attrs={"shape": []},
             optional_inputs=("Shape",), no_grad_inputs=("Index", "Shape"))
def scatter_nd(ctx, index, updates, shape_t=None, shape=()):
    import numpy as _np

    shp = [int(v) for v in (_np.asarray(shape_t) if shape_t is not None
                            else shape)]
    zeros = jnp.zeros(shp, updates.dtype)
    idx = tuple(index[..., i] for i in range(index.shape[-1]))
    return zeros.at[idx].add(updates)


@register_op("shard_index", inputs=("X",), outputs=("Out",),
             attrs={"index_num": 1, "nshards": 1, "shard_id": 0,
                    "ignore_value": -1}, grad_maker=None)
def shard_index(ctx, x, index_num=1, nshards=1, shard_id=0, ignore_value=-1):
    """Relabel ids owned by this shard; others -> ignore_value
    (shard_index_op.cc, model-parallel classification)."""
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


@register_op("sampling_id", inputs=("X",), outputs=("Out",),
             attrs={"min": 0.0, "max": 1.0, "seed": 0}, grad_maker=None,
             n_rng=1)
def sampling_id(ctx, x, min=0.0, max=1.0, seed=0):
    """Sample a column id per row from probability rows (sampling_id_op.cc)."""
    return jax.random.categorical(ctx.rng(), jnp.log(
        jnp.maximum(x, 1e-20)), axis=-1)


@register_op("random_crop", inputs=("X", "Seed"), outputs=("Out", "SeedOut"),
             attrs={"shape": [], "startup_seed": 0}, grad_maker=None,
             optional_inputs=("Seed",), n_rng=1)
def random_crop(ctx, x, seed=None, shape=(), startup_seed=0):
    """Random crop of the trailing dims to `shape` (random_crop_op.cc)."""
    shp = list(shape)
    k = len(shp)
    lead = x.ndim - k
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shp):
        hi = x.shape[lead + i] - s
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, hi + 1))
    begin = [0] * lead + starts
    sizes = list(x.shape[:lead]) + shp
    out = lax.dynamic_slice(x, begin, sizes)
    return out, (seed if seed is not None else jnp.zeros((1,), jnp.int64))


@register_op("unique_with_counts", inputs=("X",),
             outputs=("Out", "Index", "Count"),
             attrs={"dtype": 2}, grad_maker=None)
def unique_with_counts(ctx, x, dtype=2):
    """Static-shape unique (unique_with_counts_op.cc): outputs are padded
    to len(x) (XLA needs static shapes); Count is 0 beyond the distinct
    prefix."""
    flat = x.reshape(-1)
    uniq, idx, counts = jnp.unique(flat, return_inverse=True,
                                   return_counts=True, size=flat.shape[0],
                                   fill_value=flat[0])
    n_uniq = jnp.sum(counts > 0)
    counts = jnp.where(jnp.arange(flat.shape[0]) <
                       jnp.maximum(n_uniq, 1), counts, 0)
    return uniq, idx.reshape(x.shape).astype(jnp.int32), counts.astype(
        jnp.int32)


@register_op("gather_tree", inputs=("Ids", "Parents"), outputs=("Out",),
             grad_maker=None)
def gather_tree(ctx, ids, parents):
    """Backtrack beam-search parent pointers (gather_tree_op.cc):
    ids/parents [T, B, K] -> full sequences [T, B, K]."""
    T, B, K = ids.shape

    def step(beams, t):
        # beams: [B, K] current beam slot per output column
        out_t = jnp.take_along_axis(ids[t], beams, axis=1)
        beams_next = jnp.take_along_axis(parents[t], beams, axis=1)
        return beams_next, out_t

    init = jnp.tile(jnp.arange(K)[None, :], (B, 1))
    _, outs = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return outs[::-1]


@register_op("add_position_encoding", inputs=("X",), outputs=("Out",),
             attrs={"alpha": 1.0, "beta": 1.0})
def add_position_encoding(ctx, x, alpha=1.0, beta=1.0):
    """Sinusoidal position encoding added to [B, T, D] input
    (add_position_encoding_op.cc)."""
    B, T, D = x.shape
    half = D // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    if enc.shape[1] < D:
        enc = jnp.pad(enc, ((0, 0), (0, D - enc.shape[1])))
    return alpha * x + beta * enc[None, :, :].astype(x.dtype)


@register_op("selu", inputs=("X",), outputs=("Out",),
             attrs={"scale": 1.0507009873554805,
                    "alpha": 1.6732632423543772})
def selu(ctx, x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1))


@register_op("soft_relu", inputs=("X",), outputs=("Out",),
             attrs={"threshold": 40.0})
def soft_relu(ctx, x, threshold=40.0):
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


@register_op("remat_barrier", inputs=("X",), outputs=("Out",),
             duplicable_inputs=("X",), duplicable_outputs=("Out",),
             grad_maker=None)
def remat_barrier(ctx, xs):
    """Optimization barrier for activation recompute (RecomputeOptimizer):
    prevents XLA CSE from merging the backward-region forward replay with
    the original forward, which would keep the inner activations live and
    defeat rematerialization (same mechanism as jax.checkpoint's
    prevent_cse; reference recompute: backward.py:576)."""
    from jax import lax

    outs = lax.optimization_barrier(tuple(xs))
    return (list(outs),)
