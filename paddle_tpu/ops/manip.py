"""Shape/layout manipulation ops: reshape, transpose, concat, split, slice,
gather, embedding lookup, one_hot, pad, stack…

Parity: reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc,
slice_op.cc, strided_slice_op.cc, gather_op.cc, scatter_op.cc,
lookup_table_op.cc / lookup_table_v2_op.cc, one_hot_op.cc, pad_op.cc,
stack_op.cc, squeeze_op.cc, unsqueeze_op.cc, flatten_op.cc, expand_op.cc
(paddle/fluid/operators/).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..framework import _grad_var_name
from .common import attr_dtype


def _resolve_shape(x, shape):
    """Fluid reshape semantics: 0 copies the input dim, one -1 is inferred."""
    shape = list(int(s) for s in shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        total = 1
        for d in x.shape:
            total *= d
        shape[shape.index(-1)] = total // known
    return tuple(shape)


def _reshape_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    shape = list(op.attr("shape") or [])
    xshape = list(x.shape or [])
    res = []
    for i, s in enumerate(shape):
        if s == 0:
            res.append(xshape[i] if i < len(xshape) else -1)
        else:
            res.append(s)
    if -1 in res and -1 not in xshape:
        known = 1
        for s in res:
            if s != -1:
                known *= s
        total = 1
        for d in xshape:
            total *= d
        res[res.index(-1)] = total // known
    out.shape = tuple(res)
    if out.dtype is None:
        out.dtype = x.dtype
    xs_names = op.output("XShape")
    if xs_names:
        xs = block.var(xs_names[0])
        xs.shape = tuple([0] + xshape)
        if xs.dtype is None:
            xs.dtype = x.dtype


@register_op("reshape2", inputs=("X", "Shape", "ShapeTensor"),
             outputs=("Out", "XShape"),
             attrs={"shape": []},
             optional_inputs=("Shape", "ShapeTensor"),
             duplicable_inputs=("ShapeTensor",),
             infer_shape=_reshape_infer)
def reshape2(ctx, x, shape_t, shape_tensor, shape=()):
    return jnp.reshape(x, _resolve_shape(x, shape)), None


@register_op("reshape", inputs=("X", "Shape"), outputs=("Out",),
             attrs={"shape": []}, optional_inputs=("Shape",),
             infer_shape=_reshape_infer)
def reshape(ctx, x, shape_t, shape=()):
    return jnp.reshape(x, _resolve_shape(x, shape))


def _transpose_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    axis = op.attr("axis")
    if x.shape is not None:
        out.shape = tuple(x.shape[a] for a in axis)
    if out.dtype is None:
        out.dtype = x.dtype
    xs_names = op.output("XShape")
    if xs_names:
        xs = block.var(xs_names[0])
        xs.shape = tuple([0] + list(x.shape or []))
        xs.dtype = x.dtype


@register_op("transpose2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axis": []}, infer_shape=_transpose_infer)
def transpose2(ctx, x, axis=()):
    return jnp.transpose(x, axis), None


@register_op("transpose", inputs=("X",), outputs=("Out",),
             attrs={"axis": []}, infer_shape=_transpose_infer)
def transpose(ctx, x, axis=()):
    return jnp.transpose(x, axis)


@register_op("concat", inputs=("X", "AxisTensor"), outputs=("Out",),
             attrs={"axis": 0},
             duplicable_inputs=("X",), optional_inputs=("AxisTensor",))
def concat(ctx, xs, axis_tensor, axis=0):
    return jnp.concatenate(xs, axis=axis)


def _split_infer(op, block):
    x = block.var(op.input("X")[0])
    outs = [block.var(n) for n in op.output("Out")]
    axis = op.attr("axis") or 0
    num = op.attr("num") or 0
    sections = op.attr("sections") or []
    if x.shape is None:
        return
    ax = axis if axis >= 0 else axis + len(x.shape)
    dim = x.shape[ax]
    if num:
        sizes = [dim // num] * num if dim != -1 else [-1] * num
    else:
        sizes = list(sections)
    for o, s in zip(outs, sizes):
        shp = list(x.shape)
        shp[ax] = s
        o.shape = tuple(shp)
        if o.dtype is None:
            o.dtype = x.dtype


@register_op("split", inputs=("X", "AxisTensor", "SectionsTensorList"),
             outputs=("Out",),
             attrs={"axis": 0, "num": 0, "sections": []},
             optional_inputs=("AxisTensor", "SectionsTensorList"),
             duplicable_inputs=("SectionsTensorList",),
             duplicable_outputs=("Out",),
             infer_shape=_split_infer)
def split(ctx, x, axis_tensor, sections_list, axis=0, num=0, sections=()):
    if num:
        return list(jnp.split(x, num, axis=axis))
    idx = np.cumsum(sections)[:-1]
    return list(jnp.split(x, idx, axis=axis))


@register_op("slice", inputs=("Input", "StartsTensor", "EndsTensor"),
             outputs=("Out",),
             attrs={"axes": [], "starts": [], "ends": [],
                    "decrease_axis": [], "infer_flags": []},
             optional_inputs=("StartsTensor", "EndsTensor"))
def slice_op(ctx, input, starts_t, ends_t, axes=(), starts=(), ends=(),
             decrease_axis=(), infer_flags=()):
    idx = [slice(None)] * input.ndim
    for ax, st, en in zip(axes, starts, ends):
        d = input.shape[ax]
        st = int(st)
        en = int(en)
        if st < 0:
            st += d
        if en < 0:
            en += d
        en = min(en, d)
        st = min(max(st, 0), d)
        idx[ax] = slice(st, en)
    out = input[tuple(idx)]
    if decrease_axis:
        out = jnp.squeeze(out, axis=tuple(decrease_axis))
        if out.ndim == 0:
            out = out.reshape((1,))
    return out


@register_op("strided_slice", inputs=("Input",), outputs=("Out",),
             attrs={"axes": [], "starts": [], "ends": [], "strides": [],
                    "decrease_axis": [], "infer_flags": []})
def strided_slice(ctx, input, axes=(), starts=(), ends=(), strides=(),
                  decrease_axis=(), infer_flags=()):
    idx = [slice(None)] * input.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = slice(int(st), int(en), int(sd))
    out = input[tuple(idx)]
    if decrease_axis:
        out = jnp.squeeze(out, axis=tuple(decrease_axis))
    return out


def _squeeze_axes(x, axes):
    if axes:
        return tuple(a if a >= 0 else a + x.ndim for a in axes if x.shape[a if a >= 0 else a + x.ndim] == 1)
    return tuple(i for i, d in enumerate(x.shape) if d == 1)


@register_op("squeeze2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axes": []})
def squeeze2(ctx, x, axes=()):
    return jnp.squeeze(x, axis=_squeeze_axes(x, axes)), None


@register_op("squeeze", inputs=("X",), outputs=("Out",), attrs={"axes": []})
def squeeze(ctx, x, axes=()):
    return jnp.squeeze(x, axis=_squeeze_axes(x, axes))


@register_op("unsqueeze2", inputs=("X", "AxesTensor"), outputs=("Out", "XShape"),
             attrs={"axes": []}, optional_inputs=("AxesTensor",))
def unsqueeze2(ctx, x, axes_t, axes=()):
    return jnp.expand_dims(x, tuple(axes)), None


@register_op("unsqueeze", inputs=("X",), outputs=("Out",), attrs={"axes": []})
def unsqueeze(ctx, x, axes=()):
    return jnp.expand_dims(x, tuple(axes))


@register_op("flatten2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axis": 1})
def flatten2(ctx, x, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1)), None


@register_op("flatten", inputs=("X",), outputs=("Out",), attrs={"axis": 1})
def flatten(ctx, x, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1))


@register_op("flatten_contiguous_range", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"start_axis": 1, "stop_axis": -1})
def flatten_contiguous_range(ctx, x, start_axis=1, stop_axis=-1):
    stop = stop_axis if stop_axis >= 0 else x.ndim + stop_axis
    mid = 1
    for d in x.shape[start_axis:stop + 1]:
        mid *= d
    return jnp.reshape(x, x.shape[:start_axis] + (mid,) + x.shape[stop + 1:]), None


@register_op("stack", inputs=("X",), outputs=("Y",), attrs={"axis": 0},
             duplicable_inputs=("X",))
def stack(ctx, xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register_op("unstack", inputs=("X",), outputs=("Y",),
             attrs={"axis": 0, "num": 0}, duplicable_outputs=("Y",))
def unstack(ctx, x, axis=0, num=0):
    n = num or x.shape[axis]
    return [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis=axis)]


@register_op("expand", inputs=("X", "ExpandTimes"), outputs=("Out",),
             attrs={"expand_times": []}, optional_inputs=("ExpandTimes",))
def expand(ctx, x, expand_times_t, expand_times=()):
    return jnp.tile(x, tuple(int(t) for t in expand_times))


@register_op("expand_as", inputs=("X", "target_tensor"), outputs=("Out",),
             no_grad_inputs=("target_tensor",))
def expand_as(ctx, x, target):
    reps = tuple(t // s for t, s in zip(target.shape, x.shape))
    return jnp.tile(x, reps)


@register_op("tile", inputs=("X",), outputs=("Out",),
             attrs={"repeat_times": []})
def tile(ctx, x, repeat_times=()):
    return jnp.tile(x, tuple(int(t) for t in repeat_times))


@register_op("gather", inputs=("X", "Index"), outputs=("Out",),
             attrs={"overwrite": True}, no_grad_inputs=("Index",))
def gather(ctx, x, index, overwrite=True):
    idx = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, idx.astype(jnp.int32), axis=0)


@register_op("gather_nd", inputs=("X", "Index"), outputs=("Out",),
             no_grad_inputs=("Index",))
def gather_nd(ctx, x, index):
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return x[idx]


@register_op("scatter", inputs=("X", "Ids", "Updates"), outputs=("Out",),
             attrs={"overwrite": True}, no_grad_inputs=("Ids",))
def scatter(ctx, x, ids, updates, overwrite=True):
    ids = ids.reshape(-1).astype(jnp.int32)
    if overwrite:
        return x.at[ids].set(updates)
    return x.at[ids].add(updates)


@register_op("scatter_nd_add", inputs=("X", "Index", "Updates"),
             outputs=("Out",), no_grad_inputs=("Index",))
def scatter_nd_add(ctx, x, index, updates):
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return x.at[idx].add(updates)


def _lookup(table, ids, padding_idx):
    out = jnp.take(table, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


@register_op("lookup_table", inputs=("W", "Ids"), outputs=("Out",),
             attrs={"is_sparse": False, "is_distributed": False,
                    "padding_idx": -1, "remote_prefetch": False,
                    "entry_config": "", "entry": "none", "table_names": [],
                    "epmap": [], "height_sections": [], "trainer_id": 0},
             no_grad_inputs=("Ids",))
def lookup_table(ctx, w, ids, padding_idx=-1, **_):
    # fluid v1 lookup_table requires ids shape [..., 1]
    idx = ids
    if idx.ndim >= 2 and idx.shape[-1] == 1:
        idx = jnp.squeeze(idx, -1)
    return _lookup(w, idx, padding_idx)


@register_op("lookup_table_v2", inputs=("W", "Ids"), outputs=("Out",),
             attrs={"is_sparse": False, "is_distributed": False,
                    "padding_idx": -1, "remote_prefetch": False,
                    "table_names": [], "epmap": [], "trainer_id": 0},
             no_grad_inputs=("Ids",))
def lookup_table_v2(ctx, w, ids, padding_idx=-1, **_):
    return _lookup(w, ids, padding_idx)


@register_op("embedding_bag", inputs=("W", "Ids"), outputs=("Out",),
             attrs={"mode": "sum"}, no_grad_inputs=("Ids",))
def embedding_bag(ctx, w, ids, mode="sum"):
    """Bagged lookup: Out[b] = sum_k W[Ids[b, k]] over Ids >= 0 (-1 pads
    ragged bags) — the multi-hot feature read of the recommender path
    (distributed/sparse_table.py lookup_bag).  Routes to the block-sparse
    Pallas gather/sum kernel (FLAGS_use_pallas_embedding_bag, probe-gated)
    which steers the row DMA with scalar-prefetched ids so the [B, K, D]
    take-intermediate never materializes; falls back to the masked
    take+sum composition.  W grads (scatter-add) come from the fallback's
    VJP on both paths."""
    if mode != "sum":
        raise ValueError("embedding_bag supports mode='sum', got %r"
                         % (mode,))
    from ..pallas_kernels import adoption
    from ..pallas_kernels import embedding_bag as _bag

    use_kernel, _r = adoption.decide(
        "embedding_bag", flag="FLAGS_use_pallas_embedding_bag",
        checks=_bag.bag_checks(w.shape, ids.shape, w.dtype))
    if use_kernel:
        return _bag.embedding_bag(w, ids)
    return _bag.embedding_bag_reference(w, ids)


@register_op("one_hot", inputs=("X", "depth_tensor"), outputs=("Out",),
             attrs={"depth": 1, "dtype": 5, "allow_out_of_range": False},
             optional_inputs=("depth_tensor",), grad_maker=None)
def one_hot(ctx, x, depth_t, depth=1, dtype=5, allow_out_of_range=False):
    idx = x
    if idx.ndim >= 2 and idx.shape[-1] == 1:
        idx = jnp.squeeze(idx, -1)
    return jax.nn.one_hot(idx.astype(jnp.int32), depth, dtype=attr_dtype(dtype))


@register_op("one_hot_v2", inputs=("X",), outputs=("Out",),
             attrs={"depth": 1, "dtype": 5, "allow_out_of_range": False},
             grad_maker=None)
def one_hot_v2(ctx, x, depth=1, dtype=5, allow_out_of_range=False):
    return jax.nn.one_hot(x.astype(jnp.int32), depth, dtype=attr_dtype(dtype))


@register_op("pad", inputs=("X",), outputs=("Out",),
             attrs={"paddings": [], "pad_value": 0.0})
def pad(ctx, x, paddings=(), pad_value=0.0):
    cfg = [(int(paddings[2 * i]), int(paddings[2 * i + 1])) for i in range(x.ndim)]
    return jnp.pad(x, cfg, constant_values=pad_value)


@register_op("pad2d", inputs=("X",), outputs=("Out",),
             attrs={"paddings": [0, 0, 0, 0], "mode": "constant",
                    "pad_value": 0.0, "data_format": "NCHW"})
def pad2d(ctx, x, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW"):
    t, b, l, r = (int(p) for p in paddings)
    if data_format == "NCHW":
        cfg = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        cfg = [(0, 0), (t, b), (l, r), (0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, constant_values=pad_value)
    return jnp.pad(x, cfg, mode=jmode)


@register_op("reverse", inputs=("X",), outputs=("Out",), attrs={"axis": []})
def reverse(ctx, x, axis=()):
    return jnp.flip(x, axis=tuple(axis))


@register_op("roll", inputs=("X",), outputs=("Out",),
             attrs={"shifts": [], "axis": []})
def roll(ctx, x, shifts=(), axis=()):
    return jnp.roll(x, tuple(shifts), axis=tuple(axis) if axis else None)


@register_op("where", inputs=("Condition", "X", "Y"), outputs=("Out",),
             no_grad_inputs=("Condition",))
def where(ctx, cond, x, y):
    return jnp.where(cond, x, y)


@register_op("where_index", inputs=("Condition",), outputs=("Out",),
             grad_maker=None)
def where_index(ctx, cond):
    # dynamic output shape: host-side only (not jittable on TPU)
    return jnp.stack(jnp.nonzero(cond), axis=1).astype(jnp.int64)


@register_op("tril_triu", inputs=("X",), outputs=("Out",),
             attrs={"diagonal": 0, "lower": True})
def tril_triu(ctx, x, diagonal=0, lower=True):
    return jnp.tril(x, diagonal) if lower else jnp.triu(x, diagonal)


@register_op("meshgrid", inputs=("X",), outputs=("Out",),
             duplicable_inputs=("X",), duplicable_outputs=("Out",))
def meshgrid(ctx, xs):
    return list(jnp.meshgrid(*xs, indexing="ij"))


@register_op("index_select", inputs=("X", "Index"), outputs=("Out",),
             attrs={"dim": 0}, no_grad_inputs=("Index",))
def index_select(ctx, x, index, dim=0):
    return jnp.take(x, index.astype(jnp.int32), axis=dim)
