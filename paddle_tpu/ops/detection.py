"""Detection ops (parity: paddle/fluid/operators/detection/).

Static-shape XLA designs: NMS keeps a fixed-size candidate set with -1
padding (the reference emits a ragged LoDTensor); box/anchor generators and
coders are pure jnp math.  Covered: prior_box, density_prior_box,
anchor_generator, box_coder, iou_similarity, box_clip, yolo_box,
bipartite_match, target_assign, multiclass_nms, roi_align, roi_pool.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _box_area(b, offset=0.0):
    return jnp.maximum(b[..., 2] - b[..., 0] + offset, 0) * jnp.maximum(
        b[..., 3] - b[..., 1] + offset, 0)


def _iou(a, b, offset=0.0, eps=1e-10):
    """a [N,4], b [M,4] -> [N,M] IoU (xmin,ymin,xmax,ymax).
    offset=1.0 applies the pixel-coordinate +1 convention
    (bbox_util's normalized=False path)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + offset, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a, offset)[:, None] + _box_area(b, offset)[None, :] \
        - inter
    return inter / jnp.maximum(union, eps)


@register_op("iou_similarity", inputs=("X", "Y"), outputs=("Out",),
             attrs={"box_normalized": True}, grad_maker=None)
def iou_similarity(ctx, x, y, box_normalized=True):
    return _iou(x, y)


@register_op("prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"min_sizes": [], "max_sizes": [], "aspect_ratios": [1.0],
                    "variances": [0.1, 0.1, 0.2, 0.2], "flip": False,
                    "clip": False, "step_w": 0.0, "step_h": 0.0,
                    "offset": 0.5, "min_max_aspect_ratios_order": False},
             grad_maker=None)
def prior_box(ctx, feat, image, min_sizes=(), max_sizes=(),
              aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, step_w=0.0, step_h=0.0, offset=0.5,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes (detection/prior_box_op.cc): feat [N,C,H,W],
    image [N,C,IH,IW] -> boxes [H,W,A,4] normalized."""
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    sw = step_w or IW / W
    sh = step_h or IH / H
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            # reference flag (prior_box_op.cc): min square, max square, then
            # the remaining aspect-ratio boxes — matches pretrained SSD
            # weight layouts
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[list(min_sizes).index(ms)]
                whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        else:
            for ar in ars:
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
            if max_sizes:
                mx = max_sizes[list(min_sizes).index(ms)]
                whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    whs = jnp.asarray(whs, jnp.float32)          # [A, 2]
    cx = (jnp.arange(W) + offset) * sw
    cy = (jnp.arange(H) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")  # [H, W]
    centers = jnp.stack([gx, gy], -1)[:, :, None, :]  # [H,W,1,2]
    half = whs[None, None, :, :] / 2
    mins = (centers - half) / jnp.asarray([IW, IH], jnp.float32)
    maxs = (centers + half) / jnp.asarray([IW, IH], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return boxes, var


@register_op("density_prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"densities": [], "fixed_sizes": [], "fixed_ratios": [],
                    "variances": [0.1, 0.1, 0.2, 0.2], "clip": False,
                    "step_w": 0.0, "step_h": 0.0, "offset": 0.5,
                    "flatten_to_2d": False},
             grad_maker=None)
def density_prior_box(ctx, feat, image, densities=(), fixed_sizes=(),
                      fixed_ratios=(), variances=(0.1, 0.1, 0.2, 0.2),
                      clip=False, step_w=0.0, step_h=0.0, offset=0.5,
                      flatten_to_2d=False):
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    sw = step_w or IW / W
    sh = step_h or IH / H
    whs, offs = [], []
    for size, dens in zip(fixed_sizes, densities):
        for ar in (fixed_ratios or [1.0]):
            w = size * (ar ** 0.5)
            h = size / (ar ** 0.5)
            step = 1.0 / dens
            for di in range(dens):
                for dj in range(dens):
                    offs.append(((dj + 0.5) * step - 0.5,
                                 (di + 0.5) * step - 0.5))
                    whs.append((w, h))
    whs = jnp.asarray(whs, jnp.float32)
    offs = jnp.asarray(offs, jnp.float32)
    cx = (jnp.arange(W) + offset) * sw
    cy = (jnp.arange(H) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx, gy], -1)[:, :, None, :]
    centers = centers + offs[None, None] * jnp.asarray([sw, sh], jnp.float32)
    half = whs[None, None] / 2
    mins = (centers - half) / jnp.asarray([IW, IH], jnp.float32)
    maxs = (centers + half) / jnp.asarray([IW, IH], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return boxes, var


@register_op("anchor_generator", inputs=("Input",),
             outputs=("Anchors", "Variances"),
             attrs={"anchor_sizes": [64.0], "aspect_ratios": [1.0],
                    "variances": [0.1, 0.1, 0.2, 0.2],
                    "stride": [16.0, 16.0], "offset": 0.5},
             grad_maker=None)
def anchor_generator(ctx, feat, anchor_sizes=(64.0,), aspect_ratios=(1.0,),
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5):
    """RPN anchors in pixel coords (detection/anchor_generator_op.cc)."""
    H, W = feat.shape[2], feat.shape[3]
    whs = []
    for ar in aspect_ratios:
        for s in anchor_sizes:
            whs.append((s * (ar ** -0.5), s * (ar ** 0.5)))
    whs = jnp.asarray(whs, jnp.float32)
    cx = (jnp.arange(W) + offset) * stride[0]
    cy = (jnp.arange(H) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx, gy], -1)[:, :, None, :]
    half = whs[None, None] / 2
    anchors = jnp.concatenate([centers - half, centers + half], -1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return anchors, var


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",),
             attrs={"code_type": "encode_center_size",
                    "box_normalized": True, "axis": 0, "variance": []},
             optional_inputs=("PriorBoxVar",), grad_maker=None)
def box_coder(ctx, prior, prior_var, target, code_type="encode_center_size",
              box_normalized=True, axis=0, variance=()):
    """Encode/decode boxes against priors (detection/box_coder_op.cc)."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior[:, 2] - prior[:, 0] + norm
    ph = prior[:, 3] - prior[:, 1] + norm
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if prior_var is not None:
        pv = prior_var
    elif variance:
        pv = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                              prior.shape)
    else:
        pv = jnp.ones_like(prior)
    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + norm
        th = target[:, 3] - target[:, 1] + norm
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        # target rows x prior rows: [T, P, 4]
        out = jnp.stack([
            (tcx[:, None] - pcx[None]) / pw[None] / pv[None, :, 0],
            (tcy[:, None] - pcy[None]) / ph[None] / pv[None, :, 1],
            jnp.log(jnp.maximum(tw[:, None] / pw[None], 1e-10)) / pv[None, :, 2],
            jnp.log(jnp.maximum(th[:, None] / ph[None], 1e-10)) / pv[None, :, 3],
        ], axis=-1)
        return out
    # decode: target [N, P, 4] or [P, C*4] style; support [P, 4] & [N, P, 4]
    t = target
    if t.ndim == 2:
        t = t[None]
    dx = pv[None, :, 0] * t[..., 0]
    dy = pv[None, :, 1] * t[..., 1]
    dw = pv[None, :, 2] * t[..., 2]
    dh = pv[None, :, 3] * t[..., 3]
    ocx = dx * pw[None] + pcx[None]
    ocy = dy * ph[None] + pcy[None]
    ow = jnp.exp(dw) * pw[None]
    oh = jnp.exp(dh) * ph[None]
    out = jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                     ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm], axis=-1)
    return out if target.ndim == 3 else out[0]


@register_op("box_clip", inputs=("Input", "ImInfo"), outputs=("Output",),
             grad_maker=None)
def box_clip(ctx, boxes, im_info):
    """Clip boxes to image bounds (detection/box_clip_op.cc); im_info
    [N, 3] = (h, w, scale)."""
    h = im_info[0, 0] / im_info[0, 2] - 1
    w = im_info[0, 1] / im_info[0, 2] - 1
    return jnp.stack([
        jnp.clip(boxes[..., 0], 0, w), jnp.clip(boxes[..., 1], 0, h),
        jnp.clip(boxes[..., 2], 0, w), jnp.clip(boxes[..., 3], 0, h),
    ], axis=-1)


@register_op("yolo_box", inputs=("X", "ImgSize"), outputs=("Boxes", "Scores"),
             attrs={"anchors": [], "class_num": 1, "conf_thresh": 0.01,
                    "downsample_ratio": 32, "clip_bbox": True},
             grad_maker=None)
def yolo_box(ctx, x, img_size, anchors=(), class_num=1, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True):
    """YOLOv3 head decode (detection/yolo_box_op.cc): x [N, A*(5+C), H, W]
    -> boxes [N, A*H*W, 4], scores [N, A*H*W, C]."""
    N, _, H, W = x.shape
    A = len(anchors) // 2
    C = class_num
    anc = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    x = x.reshape(N, A, 5 + C, H, W)
    tx, ty, tw, th, conf = x[:, :, 0], x[:, :, 1], x[:, :, 2], x[:, :, 3], x[:, :, 4]
    cls = x[:, :, 5:]
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(tx) + gx) / W
    by = (jax.nn.sigmoid(ty) + gy) / H
    input_w = downsample_ratio * W
    input_h = downsample_ratio * H
    bw = jnp.exp(tw) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(th) * anc[None, :, 1, None, None] / input_h
    conf_s = jax.nn.sigmoid(conf)
    mask = conf_s > conf_thresh
    imgh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x0 = (bx - bw / 2) * imgw
    y0 = (by - bh / 2) * imgh
    x1 = (bx + bw / 2) * imgw
    y1 = (by + bh / 2) * imgh
    if clip_bbox:
        x0 = jnp.clip(x0, 0, imgw - 1)
        y0 = jnp.clip(y0, 0, imgh - 1)
        x1 = jnp.clip(x1, 0, imgw - 1)
        y1 = jnp.clip(y1, 0, imgh - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
    boxes = jnp.where(mask[..., None], boxes, 0.0)
    scores = jax.nn.sigmoid(cls) * conf_s[:, :, None]
    scores = jnp.where(mask[:, :, None], scores, 0.0)
    boxes = boxes.transpose(0, 1, 2, 3, 4).reshape(N, A * H * W, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(N, A * H * W, C)
    return boxes, scores


@register_op("bipartite_match", inputs=("DistMat",),
             outputs=("ColToRowMatchIndices", "ColToRowMatchDist"),
             attrs={"match_type": "bipartite", "dist_threshold": 0.5},
             grad_maker=None)
def bipartite_match(ctx, dist, match_type="bipartite", dist_threshold=0.5):
    """Greedy bipartite matching (detection/bipartite_match_op.cc):
    dist [R, C] similarity; returns per-column matched row (-1 = none)."""
    R, C = dist.shape

    def step(carry, _):
        d, col2row, col2dist = carry
        flat = jnp.argmax(d)
        r, c = flat // C, flat % C
        best = d[r, c]
        do = best > 0
        col2row = jnp.where(do, col2row.at[c].set(r), col2row)
        col2dist = jnp.where(do, col2dist.at[c].set(best), col2dist)
        d = jnp.where(do, d.at[r, :].set(-1.0).at[:, c].set(-1.0), d)
        return (d, col2row, col2dist), None

    init = (dist, jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), dist.dtype))
    (d, col2row, col2dist), _ = lax.scan(step, init, None,
                                         length=min(R, C))
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0)
        best_val = jnp.max(dist, axis=0)
        extra = (col2row < 0) & (best_val >= dist_threshold)
        col2row = jnp.where(extra, best_row.astype(jnp.int32), col2row)
        col2dist = jnp.where(extra, best_val, col2dist)
    return col2row[None, :], col2dist[None, :]


@register_op("target_assign", inputs=("X", "MatchIndices", "NegIndices"),
             outputs=("Out", "OutWeight"), attrs={"mismatch_value": 0},
             optional_inputs=("NegIndices",), grad_maker=None)
def target_assign(ctx, x, match_indices, neg_indices=None, mismatch_value=0):
    """Gather per-prior targets by match indices
    (detection/target_assign_op.cc): x [N, M, K], match [N, P]."""
    mi = match_indices.astype(jnp.int32)
    gathered = jnp.take_along_axis(
        x, jnp.clip(mi, 0, x.shape[1] - 1)[..., None], axis=1)
    matched = (mi >= 0)[..., None]
    out = jnp.where(matched, gathered, mismatch_value)
    weight = matched.astype(jnp.float32)
    return out, weight


@register_op("multiclass_nms", inputs=("BBoxes", "Scores"), outputs=("Out",),
             attrs={"background_label": 0, "score_threshold": 0.0,
                    "nms_top_k": 64, "nms_threshold": 0.3, "nms_eta": 1.0,
                    "keep_top_k": 16, "normalized": True},
             grad_maker=None)
def multiclass_nms(ctx, bboxes, scores, background_label=0,
                   score_threshold=0.0, nms_top_k=64, nms_threshold=0.3,
                   nms_eta=1.0, keep_top_k=16, normalized=True):
    """Per-class NMS (detection/multiclass_nms_op.cc).  Static-shape
    output: [N, keep_top_k, 6] rows (class, score, x0, y0, x1, y1), padded
    with class = -1 (the reference emits a ragged LoD result)."""
    N, M, _ = bboxes.shape
    C = scores.shape[1]
    k = min(nms_top_k, M)

    def nms_one_class(boxes, sc):
        val, idx = lax.top_k(sc, k)
        b = boxes[idx]
        iou = _iou(b, b)
        keep = jnp.ones((k,), bool)

        def body(i, keep):
            sup = (iou[i] > nms_threshold) & (jnp.arange(k) > i) & keep[i]
            return keep & ~sup

        keep = lax.fori_loop(0, k, body, keep)
        keep = keep & (val > score_threshold)
        return val, idx, keep

    def per_image(boxes, sc):
        fg = [c for c in range(C) if c != background_label]
        if not fg:
            # single class flagged as background: treat it as foreground
            # (a 1-class detector with the default background_label=0)
            fg = list(range(C))
        outs = []
        for c in fg:
            val, idx, keep = nms_one_class(boxes, sc[c])
            cls = jnp.full((k,), c, jnp.float32)
            row = jnp.concatenate([
                jnp.where(keep, cls, -1.0)[:, None],
                val[:, None], boxes[idx]], axis=1)
            outs.append(jnp.where(keep[:, None], row,
                                  jnp.full_like(row, -1.0)))
        allr = jnp.concatenate(outs, axis=0)
        order = jnp.argsort(-jnp.where(allr[:, 0] >= 0, allr[:, 1], -1e30))
        return allr[order][:keep_top_k]

    return jax.vmap(per_image)(bboxes, scores)


def _roi_pool_common(x, rois, spatial_scale, ph, pw, align):
    """Shared gather for roi_pool/roi_align on [N,C,H,W] with rois [R,5]
    (batch_idx, x0, y0, x1, y1)."""
    N, C, H, W = x.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        img = x[b]
        if align:
            x0 = roi[1] * spatial_scale
            y0 = roi[2] * spatial_scale
            x1 = roi[3] * spatial_scale
            y1 = roi[4] * spatial_scale
            rw = jnp.maximum(x1 - x0, 1.0)
            rh = jnp.maximum(y1 - y0, 1.0)
            # 1 sample per bin center, bilinear
            bx = x0 + (jnp.arange(pw) + 0.5) * rw / pw
            by = y0 + (jnp.arange(ph) + 0.5) * rh / ph
            gy, gx = jnp.meshgrid(by, bx, indexing="ij")
            x0i = jnp.clip(jnp.floor(gx), 0, W - 1).astype(jnp.int32)
            y0i = jnp.clip(jnp.floor(gy), 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0i + 1, 0, W - 1)
            y1i = jnp.clip(y0i + 1, 0, H - 1)
            wx = jnp.clip(gx - x0i, 0, 1)
            wy = jnp.clip(gy - y0i, 0, 1)
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                    + v10 * (1 - wx) * wy + v11 * wx * wy)
        # roi_pool: max over integer bins
        x0 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y0 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x1 = jnp.maximum(jnp.round(roi[3] * spatial_scale).astype(jnp.int32),
                         x0 + 1)
        y1 = jnp.maximum(jnp.round(roi[4] * spatial_scale).astype(jnp.int32),
                         y0 + 1)
        ys = jnp.arange(H)
        xs = jnp.arange(W)
        out = jnp.zeros((C, ph, pw), x.dtype)
        for i in range(ph):
            for j in range(pw):
                by0 = y0 + ((y1 - y0) * i) // ph
                by1 = jnp.maximum(y0 + ((y1 - y0) * (i + 1) + ph - 1) // ph,
                                  by0 + 1)
                bx0 = x0 + ((x1 - x0) * j) // pw
                bx1 = jnp.maximum(x0 + ((x1 - x0) * (j + 1) + pw - 1) // pw,
                                  bx0 + 1)
                m = ((ys[:, None] >= by0) & (ys[:, None] < by1)
                     & (xs[None, :] >= bx0) & (xs[None, :] < bx1))
                out = out.at[:, i, j].set(
                    jnp.max(jnp.where(m[None], img, -jnp.inf), axis=(1, 2)))
        return out

    return jax.vmap(one)(rois)


@register_op("roi_align", inputs=("X", "ROIs"), outputs=("Out",),
             attrs={"pooled_height": 1, "pooled_width": 1,
                    "spatial_scale": 1.0, "sampling_ratio": -1},
             no_grad_inputs=("ROIs",))
def roi_align(ctx, x, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1):
    """ROI align (detection/roi_align_op.cc); rois [R, 5] with leading
    batch index (dense replacement for the reference's LoD rois)."""
    return _roi_pool_common(x, rois, spatial_scale, pooled_height,
                            pooled_width, align=True)


@register_op("roi_pool", inputs=("X", "ROIs"), outputs=("Out", "Argmax"),
             attrs={"pooled_height": 1, "pooled_width": 1,
                    "spatial_scale": 1.0},
             no_grad_inputs=("ROIs",))
def roi_pool(ctx, x, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    out = _roi_pool_common(x, rois, spatial_scale, pooled_height,
                           pooled_width, align=False)
    return out, jnp.zeros(out.shape, jnp.int32)
