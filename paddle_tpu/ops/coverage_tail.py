"""Coverage-tail ops: the remaining REGISTER_OPERATOR surface.

Implements, with real padded-design semantics, every reference forward op
still absent after ops/longtail.py — trivial math (l1_norm_op.cc,
cos_sim_op.cc, diag_op.cc, fill_op.cc, size_op.cc), fc_op.cc,
*_batch_size_like, LoD machinery (lod_reset_op.cc, lod_rank_table_op.cc,
max_sequence_len_op.cc, reorder_lod_tensor_by_rank_op.cc,
split/merge_lod_tensor_op.cc), selected-rows/PS helpers
(merge/split_selected_rows, merge/split_ids, lookup_sparse_table),
index pooling (max_pool2d/3d_with_index), sequence tail
(sequence_reshape/slice/scatter/topk_avg_pooling, match_matrix_tensor),
the fused/fusion families (operators/fused/*), quantization tail
(fake_quantize_range_abs_max, moving_average_abs_max_scale, dequantize
variants, mkldnn-style quantize/dequantize/requantize), RNN op family
(lstm_op.cc, gru_op.cc, lstm_unit_op.cc, gru_unit_op.cc, lstmp_op.cc,
cudnn_lstm_op.cu), and executor/PS plumbing no-ops (delete_var, fake_init,
coalesce_tensor, conditional_block_infer, fetch_barrier/send_barrier/
checkpoint_notify — their work lives in the runtime here).

Sequence inputs use the padded [B, T, ...] + Length design
(ops/sequence.py).  tests/test_op_coverage.py enumerates the reference's
REGISTER_OPERATOR list and asserts only the documented engine/back-end
names remain absent.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import GradOpDesc, register_op
from ..framework import _grad_var_name

# -- trivial math ------------------------------------------------------------


@register_op("l1_norm", inputs=("X",), outputs=("Out",))
def l1_norm(ctx, x):
    """l1_norm_op.cc: Out = sum(|X|) (scalar)."""
    return jnp.sum(jnp.abs(x))


@register_op("size", inputs=("Input",), outputs=("Out",), grad_maker=None)
def size(ctx, x):
    """size_op.cc: number of elements, int64 output.  Canonicalized so the
    no-x64 default lowers to int32 without a truncation warning while x64
    builds keep true int64."""
    n = int(np.prod(x.shape))
    return jnp.asarray(n, jax.dtypes.canonicalize_dtype(jnp.int64))


@register_op("fill", inputs=(), outputs=("Out",),
             attrs={"value": [], "shape": [], "dtype": 5, "force_cpu": False},
             grad_maker=None)
def fill(ctx, value=(), shape=(), dtype=5, force_cpu=False):
    """fill_op.cc: materialize a tensor from attr data.  ``force_cpu`` is a
    placement hint that dissolves under XLA (the compiler owns placement,
    flags.py policy); the dtype attr is respected with wide types
    canonicalized rather than silently truncated."""
    from .common import attr_dtype

    np_val = np.asarray(value, attr_dtype(dtype)).reshape(
        [int(s) for s in shape])
    return jnp.asarray(
        np_val, jax.dtypes.canonicalize_dtype(np_val.dtype))


@register_op("fill_zeros_like2", inputs=("X",), outputs=("Out",),
             attrs={"dtype": -1}, grad_maker=None)
def fill_zeros_like2(ctx, x, dtype=-1):
    return jnp.zeros_like(x)


@register_op("cos_sim", inputs=("X", "Y"),
             outputs=("Out", "XNorm", "YNorm"))
def cos_sim(ctx, x, y):
    """cos_sim_op.h: row-wise cosine similarity; Y may have batch 1
    (broadcast)."""
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    dot = jnp.sum(x * y, axis=-1, keepdims=True)
    return dot / (xn * yn + 1e-12), xn, yn


@register_op("diag", inputs=("Diagonal",), outputs=("Out",),
             grad_maker=None)
def diag(ctx, d):
    """diag_op.cc: vector -> diagonal matrix."""
    return jnp.diag(d.reshape(-1))


@register_op("fc", inputs=("Input", "W", "Bias"), outputs=("Out",),
             attrs={"in_num_col_dims": 1, "activation_type": "",
                    "use_mkldnn": False, "padding_weights": False},
             optional_inputs=("Bias",))
def fc(ctx, x, w, bias=None, in_num_col_dims=1, activation_type="", **_):
    """fc_op.cc: flatten to 2d, x@w+b, optional relu."""
    lead = int(np.prod(x.shape[:in_num_col_dims]))
    out = x.reshape(lead, -1) @ w
    if bias is not None:
        out = out + bias.reshape(1, -1)
    if activation_type == "relu":
        out = jax.nn.relu(out)
    return out.reshape(tuple(x.shape[:in_num_col_dims]) + (w.shape[-1],))


@register_op("gaussian_random_batch_size_like", inputs=("Input",),
             outputs=("Out",),
             attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
                    "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
             grad_maker=None, n_rng=1)
def gaussian_random_batch_size_like(ctx, x, shape=(), input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype=5):
    from .common import attr_dtype

    shp = [int(s) for s in shape]
    shp[output_dim_idx] = x.shape[input_dim_idx]
    return mean + std * jax.random.normal(
        ctx.rng(), tuple(shp), attr_dtype(dtype))


@register_op("depthwise_conv2d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1, "output_size": [],
                    "data_format": "NCHW", "padding_algorithm": "EXPLICIT",
                    "use_cudnn": False})
def depthwise_conv2d_transpose(ctx, x, w, strides=(1, 1), paddings=(0, 0),
                               dilations=(1, 1), groups=1, output_size=(),
                               **_):
    """conv_transpose_op.cc depthwise variant: per-channel transpose conv
    (groups == channels), composed from the dense conv2d_transpose per
    channel slice."""
    from .nn import conv2d_transpose

    C = x.shape[1]
    outs = []
    for c in range(C):
        outs.append(conv2d_transpose(
            ctx, x[:, c:c + 1], w[c:c + 1], strides, paddings, dilations,
            1, "NCHW", output_size))
    return jnp.concatenate(outs, axis=1)


# -- LoD machinery (padded design) -------------------------------------------


@register_op("lod_reset", inputs=("X", "Y"), outputs=("Out",),
             attrs={"target_lod": [], "append": False},
             optional_inputs=("Y",), no_grad_inputs=("Y",))
def lod_reset(ctx, x, y, target_lod=(), append=False):
    """lod_reset_op.cc: replace LoD metadata.  Padded tensors carry
    lengths out-of-band, so the data passes through unchanged."""
    return x


@register_op("lod_rank_table", inputs=("X", "Length"), outputs=("Out",),
             optional_inputs=("Length",), grad_maker=None)
def lod_rank_table(ctx, x, length):
    """lod_rank_table_op.cc: rows sorted by sequence length, descending;
    returns [N, 2] (original_index, length)."""
    B = x.shape[0]
    lens = (length.reshape(-1).astype(jnp.int64) if length is not None
            else jnp.full((B,), x.shape[1], jnp.int64))
    order = jnp.argsort(-lens, stable=True)
    return jnp.stack([order.astype(jnp.int64), lens[order]], axis=1)


@register_op("max_sequence_len", inputs=("RankTable",), outputs=("Out",),
             grad_maker=None)
def max_sequence_len(ctx, table):
    """max_sequence_len_op.cc: longest length in a rank table."""
    return table[0, 1]


@register_op("reorder_lod_tensor_by_rank", inputs=("X", "RankTable"),
             outputs=("Out",), no_grad_inputs=("RankTable",))
def reorder_lod_tensor_by_rank(ctx, x, table):
    """reorder_lod_tensor_by_rank_op.cc: permute rows into rank order."""
    return x[table[:, 0].astype(jnp.int32)]


@register_op("split_lod_tensor", inputs=("X", "Mask"),
             outputs=("OutTrue", "OutFalse"),
             attrs={"level": 0}, no_grad_inputs=("Mask",))
def split_lod_tensor(ctx, x, mask, level=0):
    """split_lod_tensor_op.cc (IfElse plumbing): route rows by boolean
    mask.  Static shapes forbid compaction, so each branch keeps the full
    batch with non-selected rows zeroed — merge_lod_tensor reassembles
    exactly."""
    m = mask.reshape(-1).astype(bool)
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mt = m.reshape(shape)
    return jnp.where(mt, x, 0), jnp.where(mt, 0, x)


def _merge_lod(ctx, x, mask, in_true, in_false, level=0):
    m = mask.reshape(-1).astype(bool)
    shape = (in_true.shape[0],) + (1,) * (in_true.ndim - 1)
    return jnp.where(m.reshape(shape), in_true, in_false)


register_op("merge_lod_tensor", inputs=("X", "Mask", "InTrue", "InFalse"),
            outputs=("Out",), attrs={"level": 0},
            optional_inputs=("X",),
            no_grad_inputs=("X", "Mask"))(_merge_lod)
register_op("merge_lod_tensor_infer",
            inputs=("X", "Mask", "InTrue", "InFalse"), outputs=("Out",),
            attrs={"level": 0}, optional_inputs=("X",),
            grad_maker=None)(_merge_lod)


# -- selected-rows / PS id helpers -------------------------------------------


@register_op("merge_selected_rows", inputs=("X",), outputs=("Out",))
def merge_selected_rows(ctx, x):
    """merge_selected_rows_op.cc: combine duplicate rows.  Row-sets ride
    dense here (core/scope.py SelectedRows note), where duplicates are
    already summed — identity."""
    return x


@register_op("split_selected_rows", inputs=("X",), outputs=("Out",),
             attrs={"height_sections": []}, duplicable_outputs=("Out",),
             grad_maker=None)
def split_selected_rows(ctx, x, height_sections=()):
    """split_selected_rows_op.cc: slice the dense row space into height
    sections (PS parameter sharding)."""
    outs, off = [], 0
    for h in height_sections:
        outs.append(x[off:off + int(h)])
        off += int(h)
    return (outs,)


@register_op("split_ids", inputs=("Ids",), outputs=("Out",),
             duplicable_inputs=("Ids",), duplicable_outputs=("Out",),
             grad_maker=None)
def split_ids(ctx, ids_list):
    """split_ids_op.cc: shard ids round-robin across N outputs (PS id
    dispatch).  Static shapes keep each shard full-size with non-owned
    slots marked -1."""
    op = ctx.op if ctx is not None else None
    n = len(op.output("Out")) if op is not None else 1
    ids = ids_list[0].reshape(-1)
    outs = []
    for k in range(n):
        mine = (ids % n) == k
        outs.append(jnp.where(mine, ids, -1))
    return (outs,)


@register_op("merge_ids", inputs=("Ids", "Rows", "X"), outputs=("Out",),
             duplicable_inputs=("Ids", "Rows", "X"),
             duplicable_outputs=("Out",), grad_maker=None)
def merge_ids(ctx, ids_list, rows_list, x_list):
    """merge_ids_op.cc: gather each id's row from the shard that owns it
    (inverse of split_ids; rows hold the shard's id order)."""
    n = len(x_list)
    ids = ids_list[0].reshape(-1)
    dim = x_list[0].shape[-1]
    out = jnp.zeros((ids.shape[0], dim), x_list[0].dtype)
    for k in range(n):
        rows = rows_list[k].reshape(-1)
        # position of each id within shard k's row list (-1 padded)
        hit = ids[:, None] == rows[None, :]
        pos = jnp.argmax(hit, axis=1)
        found = hit.any(axis=1) & ((ids % n) == k)
        vals = x_list[k][pos]
        out = jnp.where(found[:, None], vals, out)
    return ([out],)


@register_op("split_byref", inputs=("X",), outputs=("Out",),
             attrs={"sections": [], "num": 0, "axis": 0},
             duplicable_outputs=("Out",), grad_maker=None)
def split_byref(ctx, x, sections=(), num=0, axis=0):
    """split_byref_op.cc: split sharing storage; XLA is functional, so it
    equals split along dim 0."""
    from .manip import split as _split

    return _split(ctx, x, None, None, sections=list(sections), num=num,
                  axis=0)


@register_op("lookup_sparse_table", inputs=("W", "Ids"), outputs=("Out",),
             attrs={"is_test": False, "value_names": [], "padding_idx": -1},
             no_grad_inputs=("Ids",))
def lookup_sparse_table(ctx, w, ids, is_test=False, **_):
    """lookup_sparse_table_op.cc: embedding pull from the (auto-growing)
    PS table; the distributed path is distributed/sparse_table.py — here
    the local dense view is gathered."""
    flat = ids.reshape(-1).astype(jnp.int32)
    return jnp.take(w, flat, axis=0).reshape(
        tuple(ids.shape) + (w.shape[-1],))


# -- pooling with indices ----------------------------------------------------


@register_op("max_pool2d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                    "global_pooling": False, "adaptive": False})
def max_pool2d_with_index(ctx, x, ksize=(2, 2), strides=(2, 2),
                          paddings=(0, 0), global_pooling=False,
                          adaptive=False):
    """max_pool_with_index_op.cc: max pool + flat argmax indices (consumed
    by unpool).  Index extraction: per output cell, argmax over its input
    window via lexicographic (value, -position) encoding on a
    position-preserving gather."""
    N, C, H, W = x.shape
    if global_pooling:
        ksize = (H, W)
        strides, paddings = (H, W), (0, 0)
    kh, kw = int(ksize[0]), int(ksize[1])
    sh, sw = int(strides[0]), int(strides[1])
    ph, pw = int(paddings[0]), int(paddings[1])
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=-np.inf)
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    # gather windows [N, C, oh, ow, kh*kw]
    wins = []
    poss = []
    for di in range(kh):
        for dj in range(kw):
            sl = xp[:, :, di:di + oh * sh:sh, dj:dj + ow * sw:sw]
            wins.append(sl)
            ii = jnp.arange(oh) * sh + di - ph
            jj = jnp.arange(ow) * sw + dj - pw
            p = ii[:, None] * W + jj[None, :]
            poss.append(jnp.broadcast_to(p, (N, C, oh, ow)))
    stack = jnp.stack(wins, axis=-1)
    pstack = jnp.stack(poss, axis=-1)
    k = jnp.argmax(stack, axis=-1)
    out = jnp.take_along_axis(stack, k[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(pstack, k[..., None], axis=-1)[..., 0]
    return out, mask.astype(jnp.int32)


@register_op("max_pool3d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": [2, 2, 2], "strides": [2, 2, 2],
                    "paddings": [0, 0, 0], "global_pooling": False,
                    "adaptive": False})
def max_pool3d_with_index(ctx, x, ksize=(2, 2, 2), strides=(2, 2, 2),
                          paddings=(0, 0, 0), global_pooling=False,
                          adaptive=False):
    """3d variant of max_pool2d_with_index (max_pool_with_index_op.cc)."""
    N, C, D, H, W = x.shape
    if global_pooling:
        ksize, strides, paddings = (D, H, W), (D, H, W), (0, 0, 0)
    kd, kh, kw = [int(v) for v in ksize]
    sd, sh, sw = [int(v) for v in strides]
    pd, ph, pw = [int(v) for v in paddings]
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)),
                 constant_values=-np.inf)
    od = (D + 2 * pd - kd) // sd + 1
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    wins, poss = [], []
    for dd in range(kd):
        for di in range(kh):
            for dj in range(kw):
                sl = xp[:, :, dd:dd + od * sd:sd, di:di + oh * sh:sh,
                        dj:dj + ow * sw:sw]
                wins.append(sl)
                kk = jnp.arange(od) * sd + dd - pd
                ii = jnp.arange(oh) * sh + di - ph
                jj = jnp.arange(ow) * sw + dj - pw
                p = (kk[:, None, None] * H + ii[None, :, None]) * W + \
                    jj[None, None, :]
                poss.append(jnp.broadcast_to(p, (N, C, od, oh, ow)))
    stack = jnp.stack(wins, axis=-1)
    pstack = jnp.stack(poss, axis=-1)
    k = jnp.argmax(stack, axis=-1)
    out = jnp.take_along_axis(stack, k[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(pstack, k[..., None], axis=-1)[..., 0]
    return out, mask.astype(jnp.int32)


# -- sequence tail -----------------------------------------------------------


@register_op("sequence_reshape", inputs=("X",), outputs=("Out",),
             attrs={"new_dim": 1})
def sequence_reshape(ctx, x, new_dim=1):
    """sequence_reshape_op.cc: refactor [B, T, D] tokens so the feature
    width becomes new_dim (total elements per row preserved)."""
    B = x.shape[0]
    return x.reshape(B, -1, int(new_dim))


@register_op("sequence_slice", inputs=("X", "Offset", "Length"),
             outputs=("Out",), no_grad_inputs=("Offset", "Length"))
def sequence_slice(ctx, x, offset, length):
    """sequence_slice_op.cc: per-row [offset, offset+length) window along
    time, re-padded to the max kept length."""
    B, T = x.shape[0], x.shape[1]
    off = offset.reshape(-1).astype(jnp.int32)
    ln = length.reshape(-1).astype(jnp.int32)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    keep = (t >= off[:, None]) & (t < (off + ln)[:, None])
    # shift each row left by its offset via gather
    gather_idx = (t + off[:, None]) % T
    shifted = jnp.take_along_axis(
        x, gather_idx.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
    mask = (t < ln[:, None]).reshape((B, T) + (1,) * (x.ndim - 2))
    return shifted * mask.astype(x.dtype)


@register_op("sequence_scatter", inputs=("X", "Ids", "Updates"),
             outputs=("Out",), no_grad_inputs=("Ids",))
def sequence_scatter(ctx, x, ids, updates):
    """sequence_scatter_op.cc: per-row scatter-add of updates at time
    indices ids: X [B, D], Ids [B, T], Updates [B, T]."""
    B = x.shape[0]
    bidx = jnp.arange(B)[:, None]
    return x.at[bidx, ids.reshape(B, -1).astype(jnp.int32)].add(
        updates.reshape(B, -1).astype(x.dtype))


@register_op("sequence_topk_avg_pooling",
             inputs=("X", "ROW", "COLUMN"),
             outputs=("Out", "pos"),
             attrs={"topks": [1], "channel_num": 1},
             optional_inputs=("ROW", "COLUMN"),
             no_grad_inputs=("ROW", "COLUMN"))
def sequence_topk_avg_pooling(ctx, x, row, column, topks=(1,),
                              channel_num=1):
    """sequence_topk_avg_pooling_op.cc: per channel, average of the top-k
    values over the trailing axis, one output column per k."""
    B = x.shape[0]
    flat = x.reshape(B, channel_num, -1)
    L = flat.shape[-1]
    srt = jnp.sort(flat, axis=-1)[..., ::-1]
    outs = []
    for k in topks:
        k = min(int(k), L)
        outs.append(jnp.mean(srt[..., :k], axis=-1))
    return (jnp.stack(outs, axis=-1).reshape(B, -1),
            jnp.zeros((1,), jnp.int32))


@register_op("match_matrix_tensor", inputs=("X", "Y", "W"),
             outputs=("Out", "Tmp"), attrs={"dim_t": 1})
def match_matrix_tensor(ctx, x, y, w, dim_t=1):
    """match_matrix_tensor_op.cc (text matching): X [B, Tx, D1],
    Y [B, Ty, D2], W [D1, dim_t, D2]; Out[b,t,i,j] = x_i W_t y_j."""
    tmp = jnp.einsum("bid,dte->bite", x, w.reshape(
        x.shape[-1], int(dim_t), y.shape[-1]))
    out = jnp.einsum("bite,bje->btij", tmp, y)
    B = x.shape[0]
    return out.reshape(B, -1), tmp.reshape(B, -1)


# -- fused / fusion families -------------------------------------------------


def _act_by_name(name):
    return {"relu": jax.nn.relu, "tanh": jnp.tanh,
            "sigmoid": jax.nn.sigmoid, "identity": lambda v: v,
            "": lambda v: v}[name]


@register_op("fused_elemwise_activation", inputs=("X", "Y"),
             outputs=("Out", "IntermediateOut"),
             attrs={"functor_list": [], "axis": -1, "scale": 1.0,
                    "save_intermediate_out": False})
def fused_elemwise_activation(ctx, x, y, functor_list=(), axis=-1,
                              scale=1.0, save_intermediate_out=False):
    """fused_elemwise_activation_op.cc: compose f1(f2(x, y)) from
    {elementwise_add,mul} x {relu,scale,tanh,sigmoid}."""
    from .math import bcast_y

    def apply_one(name, a, b=None):
        if name.startswith("elementwise_"):
            fn = {"elementwise_add": jnp.add,
                  "elementwise_mul": jnp.multiply}[name]
            return fn(a, bcast_y(a, b, axis))
        if name == "scale":
            return a * scale
        return _act_by_name(name)(a)

    f1, f2 = (list(functor_list) + ["identity", "identity"])[:2]
    if f2.startswith("elementwise_"):
        inter = apply_one(f2, x, y)
        out = apply_one(f1, inter)
    else:
        inter = apply_one(f2, y)
        out = apply_one(f1, x, inter)
    return out, inter


@register_op("fused_embedding_seq_pool", inputs=("W", "Ids"),
             outputs=("Out",),
             attrs={"combiner": "sum", "is_sparse": False,
                    "padding_idx": -1},
             no_grad_inputs=("Ids",))
def fused_embedding_seq_pool(ctx, w, ids, combiner="sum", is_sparse=False,
                             padding_idx=-1):
    """fused_embedding_seq_pool_op.cc: embedding lookup + sum over time:
    Ids [B, T, 1] -> Out [B, D]."""
    flat = ids.reshape(ids.shape[0], -1).astype(jnp.int32)
    emb = jnp.take(w, flat, axis=0)
    if padding_idx >= 0:
        emb = emb * (flat != padding_idx)[..., None].astype(emb.dtype)
    return jnp.sum(emb, axis=1)


@register_op("fused_fc_elementwise_layernorm",
             inputs=("X", "W", "Y", "Bias0", "Bias1", "Scale"),
             outputs=("Out", "Mean", "Variance"),
             attrs={"x_num_col_dims": 1, "activation_type": "",
                    "begin_norm_axis": 1, "epsilon": 1e-5},
             optional_inputs=("Bias0", "Bias1", "Scale"))
def fused_fc_elementwise_layernorm(ctx, x, w, y, bias0=None, bias1=None,
                                   scale=None, x_num_col_dims=1,
                                   activation_type="", begin_norm_axis=1,
                                   epsilon=1e-5):
    """fused_fc_elementwise_layernorm_op.cc: layer_norm(fc(x) + y)."""
    out = fc(ctx, x, w, bias0, x_num_col_dims, activation_type)
    z = out + y
    axes = tuple(range(begin_norm_axis, z.ndim))
    m = jnp.mean(z, axis=axes, keepdims=True)
    v = jnp.var(z, axis=axes, keepdims=True)
    n = (z - m) / jnp.sqrt(v + epsilon)
    tail = z.shape[begin_norm_axis:]
    if scale is not None:
        n = n * scale.reshape(tail)
    if bias1 is not None:
        n = n + bias1.reshape(tail)
    lead = z.shape[:begin_norm_axis]
    return n, m.reshape(lead), v.reshape(lead)


def _gru_scan(x_proj, h0, wh, act, gate_act, origin_mode, reverse=False):
    """Shared GRU recurrence (gru_op.cc math): x_proj [B, T, 3D]
    pre-projected input, wh [D, 3D] packed {update+reset | candidate}."""
    B, T, D3 = x_proj.shape
    D = D3 // 3
    w_ur, w_c = wh[:, :2 * D], wh[:, 2 * D:]

    def step(h, xt):
        ur = xt[:, :2 * D] + h @ w_ur
        u = gate_act(ur[:, :D])
        r = gate_act(ur[:, D:])
        c = act(xt[:, 2 * D:] + (r * h) @ w_c)
        if origin_mode:
            h_new = (1.0 - u) * h + u * c
        else:
            h_new = u * h + (1.0 - u) * c
        return h_new, h_new

    xs = jnp.swapaxes(x_proj, 0, 1)
    hT, hs = lax.scan(step, h0, xs, reverse=bool(reverse))
    return jnp.swapaxes(hs, 0, 1), hT


@register_op("gru", inputs=("Input", "H0", "Weight", "Bias"),
             outputs=("BatchGate", "BatchResetHiddenPrev", "BatchHidden",
                      "Hidden"),
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "is_reverse": False, "origin_mode": False},
             optional_inputs=("H0", "Bias"))
def gru(ctx, x, h0, weight, bias, activation="tanh",
        gate_activation="sigmoid", is_reverse=False, origin_mode=False):
    """gru_op.cc: Input [B, T, 3D] (pre-projected), Weight [D, 3D],
    Bias [1, 3D]."""
    D = weight.shape[0]
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)
    h0_ = h0 if h0 is not None else jnp.zeros((x.shape[0], D), x.dtype)
    hs, _ = _gru_scan(x, h0_, weight, _act_by_name(activation),
                      _act_by_name(gate_activation), origin_mode,
                      is_reverse)
    z = jnp.zeros((1,), x.dtype)
    return x, z, hs, hs


@register_op("gru_unit",
             inputs=("Input", "HiddenPrev", "Weight", "Bias"),
             outputs=("Gate", "ResetHiddenPrev", "Hidden"),
             attrs={"activation": 2, "gate_activation": 1,
                    "origin_mode": False},
             optional_inputs=("Bias",))
def gru_unit_op(ctx, x, h_prev, weight, bias, activation=2,
                gate_activation=1, origin_mode=False):
    """gru_unit_op.cc: one GRU step.  Input [B, 3D], Weight [D, 3D]
    packed {u,r | c}; activation enums: 0=identity 1=sigmoid 2=tanh 3=relu
    (gru_unit_op.h ActivationType)."""
    enum_act = {0: lambda v: v, 1: jax.nn.sigmoid, 2: jnp.tanh,
                3: jax.nn.relu}
    act, gact = enum_act[int(activation)], enum_act[int(gate_activation)]
    D = weight.shape[0]
    if bias is not None:
        x = x + bias.reshape(1, -1)
    ur = x[:, :2 * D] + h_prev @ weight[:, :2 * D]
    u, r = gact(ur[:, :D]), gact(ur[:, D:])
    rh = r * h_prev
    c = act(x[:, 2 * D:] + rh @ weight[:, 2 * D:])
    if origin_mode:
        h = (1.0 - u) * h_prev + u * c
    else:
        h = u * h_prev + (1.0 - u) * c
    return jnp.concatenate([u, r, c], axis=1), rh, h


def _lstm_scan(x_proj, h0, c0, wh, acts, reverse=False, proj=None,
               use_peepholes=False, pw=None):
    """Shared LSTM recurrence (lstm_op.cc / lstmp_op.cc): x_proj
    [B, T, 4D] pre-projected; gate order {input, forget, candidate,
    output} (lstm_op.cc Weight doc); wh [D or P, 4D]."""
    gate_act, cell_act, cand_act = acts
    D = wh.shape[1] // 4

    def step(carry, xt):
        h, c = carry
        g = xt + h @ wh
        i = gate_act(g[:, :D])
        f = gate_act(g[:, D:2 * D])
        cand = cand_act(g[:, 2 * D:3 * D])
        o = gate_act(g[:, 3 * D:])
        c_new = f * c + i * cand
        h_new = o * cell_act(c_new)
        if proj is not None:
            h_new = h_new @ proj
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(x_proj, 0, 1)
    (_hT, _cT), (hs, cs) = lax.scan(step, (h0, c0), xs,
                                    reverse=bool(reverse))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@register_op("lstm", inputs=("Input", "H0", "C0", "Weight", "Bias"),
             outputs=("Hidden", "Cell", "BatchGate", "BatchCellPreAct"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"},
             optional_inputs=("H0", "C0", "Bias"))
def lstm_op(ctx, x, h0, c0, weight, bias, use_peepholes=True,
            is_reverse=False, gate_activation="sigmoid",
            cell_activation="tanh", candidate_activation="tanh"):
    """lstm_op.cc: Input [B, T, 4D] pre-projected, Weight [D, 4D]
    recurrent.  Peephole connections are folded into the gate bias
    approximation (documented deviation: XLA-friendly single-matmul
    recurrence)."""
    D = weight.shape[0]
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)[..., :4 * D]
    B = x.shape[0]
    h0_ = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    c0_ = c0 if c0 is not None else jnp.zeros((B, D), x.dtype)
    acts = (_act_by_name(gate_activation), _act_by_name(cell_activation),
            _act_by_name(candidate_activation))
    hs, cs = _lstm_scan(x, h0_, c0_, weight, acts, is_reverse)
    z = jnp.zeros((1,), x.dtype)
    return hs, cs, z, z


@register_op("lstmp",
             inputs=("Input", "H0", "C0", "Weight", "ProjWeight", "Bias"),
             outputs=("Projection", "Cell", "BatchGate",
                      "BatchCellPreAct", "BatchHidden"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "cell_clip": 0.0, "proj_clip": 0.0,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh",
                    "proj_activation": "tanh"},
             optional_inputs=("H0", "C0", "Bias"))
def lstmp_op(ctx, x, h0, c0, weight, proj_weight, bias,
             use_peepholes=True, is_reverse=False, cell_clip=0.0,
             proj_clip=0.0, gate_activation="sigmoid",
             cell_activation="tanh", candidate_activation="tanh",
             proj_activation="tanh"):
    """lstmp_op.cc: LSTM with projection; recurrent state is the
    projection r [B, P] = proj_act(h @ ProjWeight [D, P])."""
    D = weight.shape[1] // 4
    P = proj_weight.shape[1]
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)[..., :4 * D]
    B = x.shape[0]
    pact = _act_by_name(proj_activation)
    proj = proj_weight
    h0_ = h0 if h0 is not None else jnp.zeros((B, P), x.dtype)
    c0_ = c0 if c0 is not None else jnp.zeros((B, D), x.dtype)
    acts = (_act_by_name(gate_activation), _act_by_name(cell_activation),
            _act_by_name(candidate_activation))

    def step(carry, xt):
        r, c = carry
        g = xt + r @ weight
        i = acts[0](g[:, :D])
        f = acts[0](g[:, D:2 * D])
        cand = acts[2](g[:, 2 * D:3 * D])
        o = acts[0](g[:, 3 * D:])
        c_new = f * c + i * cand
        if cell_clip:
            c_new = jnp.clip(c_new, -cell_clip, cell_clip)
        h_new = o * acts[1](c_new)
        r_new = pact(h_new @ proj)
        if proj_clip:
            r_new = jnp.clip(r_new, -proj_clip, proj_clip)
        return (r_new, c_new), (r_new, c_new)

    xs = jnp.swapaxes(x, 0, 1)
    _fin, (rs, cs) = lax.scan(step, (h0_, c0_), xs, reverse=bool(is_reverse))
    z = jnp.zeros((1,), x.dtype)
    return (jnp.swapaxes(rs, 0, 1), jnp.swapaxes(cs, 0, 1), z, z, z)


@register_op("lstm_unit", inputs=("X", "C_prev"), outputs=("C", "H"),
             attrs={"forget_bias": 0.0})
def lstm_unit_op(ctx, x, c_prev, forget_bias=0.0):
    """lstm_unit_op.cc: one LSTM step over pre-projected gates X [B, 4D],
    gate order {input, candidate(tanh), forget, output}
    (lstm_unit_op.h)."""
    D = c_prev.shape[-1]
    i = jax.nn.sigmoid(x[:, :D])
    g = jnp.tanh(x[:, D:2 * D])
    f = jax.nn.sigmoid(x[:, 2 * D:3 * D] + forget_bias)
    o = jax.nn.sigmoid(x[:, 3 * D:])
    c = f * c_prev + i * g
    return c, o * jnp.tanh(c)


@register_op("cudnn_lstm",
             inputs=("Input", "InitH", "InitC", "W"),
             outputs=("Out", "last_h", "last_c", "Reserve", "StateOut"),
             attrs={"max_len": 0, "hidden_size": 0, "num_layers": 1,
                    "is_bidirec": False, "is_test": False,
                    "dropout_prob": 0.0, "seed": 0},
             optional_inputs=("InitH", "InitC"))
def cudnn_lstm(ctx, x, init_h, init_c, w, max_len=0, hidden_size=0,
               num_layers=1, is_bidirec=False, is_test=False,
               dropout_prob=0.0, seed=0):
    """cudnn_lstm_op.cu: stacked LSTM over a packed weight blob.  The
    cuDNN blob layout per (layer, direction) is
    [Wx (F x 4D), Wh (D x 4D), bias (8D)] flattened; the same slicing is
    applied here, then each layer runs the shared scan."""
    B, T, F = x.shape
    D = int(hidden_size)
    flat = w.reshape(-1)
    off = 0
    ndir = 2 if is_bidirec else 1
    out = x
    lasth, lastc = [], []
    acts = (jax.nn.sigmoid, jnp.tanh, jnp.tanh)
    for layer in range(int(num_layers)):
        fin = out.shape[-1]
        dir_outs = []
        for d in range(ndir):
            wx = flat[off:off + fin * 4 * D].reshape(fin, 4 * D)
            off += fin * 4 * D
            wh = flat[off:off + D * 4 * D].reshape(D, 4 * D)
            off += D * 4 * D
            b = flat[off:off + 8 * D]
            off += 8 * D
            proj = out @ wx + (b[:4 * D] + b[4 * D:]).reshape(1, 1, -1)
            h0 = (init_h[layer * ndir + d] if init_h is not None
                  else jnp.zeros((B, D), x.dtype))
            c0 = (init_c[layer * ndir + d] if init_c is not None
                  else jnp.zeros((B, D), x.dtype))
            hs, cs = _lstm_scan(proj, h0, c0, wh, acts, reverse=(d == 1))
            dir_outs.append(hs)
            lasth.append(hs[:, 0 if d == 1 else -1])
            lastc.append(cs[:, 0 if d == 1 else -1])
        out = (jnp.concatenate(dir_outs, axis=-1) if ndir == 2
               else dir_outs[0])
    z = jnp.zeros((1,), x.dtype)
    return (out, jnp.stack(lasth), jnp.stack(lastc), z, z)


@register_op("fusion_gru",
             inputs=("X", "H0", "WeightX", "WeightH", "Bias"),
             outputs=("ReorderedH0", "XX", "BatchedInput", "BatchedOut",
                      "Hidden"),
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "is_reverse": False, "use_seq": True,
                    "origin_mode": False},
             optional_inputs=("H0", "Bias"))
def fusion_gru(ctx, x, h0, wx, wh, bias, activation="tanh",
               gate_activation="sigmoid", is_reverse=False, use_seq=True,
               origin_mode=False):
    """fusion_gru_op.cc: fc(x) + gru fused: X [B, T, F], WeightX [F, 3D],
    WeightH [D, 3D]."""
    proj = jnp.einsum("btf,fd->btd", x, wx)
    if bias is not None:
        proj = proj + bias.reshape(1, 1, -1)
    D = wh.shape[0]
    h0_ = h0 if h0 is not None else jnp.zeros((x.shape[0], D), x.dtype)
    hs, _ = _gru_scan(proj, h0_, wh, _act_by_name(activation),
                      _act_by_name(gate_activation), origin_mode,
                      is_reverse)
    z = jnp.zeros((1,), x.dtype)
    return z, z, z, z, hs


@register_op("fusion_lstm",
             inputs=("X", "H0", "C0", "WeightX", "WeightH", "Bias"),
             outputs=("Hidden", "Cell", "XX", "BatchedInput",
                      "BatchedHidden", "BatchedCell", "ReorderedH0",
                      "ReorderedC0"),
             attrs={"use_peepholes": False, "is_reverse": False,
                    "use_seq": True, "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"},
             optional_inputs=("H0", "C0", "Bias"))
def fusion_lstm(ctx, x, h0, c0, wx, wh, bias, use_peepholes=False,
                is_reverse=False, use_seq=True, gate_activation="sigmoid",
                cell_activation="tanh", candidate_activation="tanh"):
    """fusion_lstm_op.cc: fc(x) + lstm fused: WeightX [F, 4D],
    WeightH [D, 4D]."""
    proj = jnp.einsum("btf,fd->btd", x, wx)
    if bias is not None:
        proj = proj + bias.reshape(1, 1, -1)[..., :wh.shape[1]]
    D = wh.shape[0]
    B = x.shape[0]
    h0_ = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    c0_ = c0 if c0 is not None else jnp.zeros((B, D), x.dtype)
    acts = (_act_by_name(gate_activation), _act_by_name(cell_activation),
            _act_by_name(candidate_activation))
    hs, cs = _lstm_scan(proj, h0_, c0_, wh, acts, is_reverse)
    z = jnp.zeros((1,), x.dtype)
    return hs, cs, z, z, z, z, z, z


@register_op("fused_embedding_fc_lstm",
             inputs=("Ids", "Embeddings", "WeightH", "Bias", "H0", "C0"),
             outputs=("Hidden", "Cell", "XX", "BatchedInput",
                      "BatchedHidden", "BatchedCell", "ReorderedH0",
                      "ReorderedC0"),
             attrs={"use_peepholes": False, "is_reverse": False,
                    "use_seq": True, "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"},
             optional_inputs=("H0", "C0", "Bias"),
             no_grad_inputs=("Ids",))
def fused_embedding_fc_lstm(ctx, ids, embeddings, wh, bias, h0, c0,
                            **attrs):
    """fused_embedding_fc_lstm_op.cc: the embedding table already holds
    the fc projection (rows are [4D] gate pre-activations); gather + lstm."""
    B = ids.shape[0]
    flat = ids.reshape(B, -1).astype(jnp.int32)
    proj = jnp.take(embeddings, flat, axis=0)
    if bias is not None:
        proj = proj + bias.reshape(1, 1, -1)[..., :wh.shape[1]]
    D = wh.shape[0]
    h0_ = h0 if h0 is not None else jnp.zeros((B, D), proj.dtype)
    c0_ = c0 if c0 is not None else jnp.zeros((B, D), proj.dtype)
    acts = (jax.nn.sigmoid, jnp.tanh, jnp.tanh)
    hs, cs = _lstm_scan(proj, h0_, c0_, wh, acts,
                        attrs.get("is_reverse", False))
    z = jnp.zeros((1,), proj.dtype)
    return hs, cs, z, z, z, z, z, z


@register_op("fusion_repeated_fc_relu", inputs=("X", "W", "Bias"),
             outputs=("ReluOut", "Out"),
             duplicable_inputs=("W", "Bias"),
             duplicable_outputs=("ReluOut",))
def fusion_repeated_fc_relu(ctx, x, ws, biases):
    """fusion_repeated_fc_relu_op.cc:118-139: chain of fc+bias+relu, relu
    applied to EVERY layer including the last (all kernel calls are
    fc_relu); ReluOut holds the first N-1 activations."""
    relus = []
    out = x
    for i, (w, b) in enumerate(zip(ws, biases)):
        out = jax.nn.relu(out @ w + b.reshape(1, -1))
        if i + 1 < len(ws):
            relus.append(out)
    return (relus, out)


@register_op("fusion_seqconv_eltadd_relu",
             inputs=("X", "Filter", "Bias"),
             outputs=("Out", "ColMat"),
             attrs={"contextLength": 1, "contextStart": 0,
                    "contextStride": 1})
def fusion_seqconv_eltadd_relu(ctx, x, filt, bias, contextLength=1,
                               contextStart=0, contextStride=1):
    """fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu."""
    from .sequence import sequence_conv

    out = sequence_conv(ctx, x, filt, None, None,
                        contextLength=contextLength,
                        contextStart=contextStart,
                        contextStride=contextStride)
    out = jax.nn.relu(out + bias.reshape(1, 1, -1))
    return out, jnp.zeros((1,), x.dtype)


@register_op("fusion_seqexpand_concat_fc",
             inputs=("X", "FCWeight", "FCBias"),
             outputs=("Out", "FCOut"),
             attrs={"fc_activation": "relu"},
             duplicable_inputs=("X",), optional_inputs=("FCBias",))
def fusion_seqexpand_concat_fc(ctx, xs, w, b, fc_activation="relu"):
    """fusion_seqexpand_concat_fc_op.cc: expand the [B, D] side inputs
    over time, concat with the [B, T, D0] sequence, fc + act."""
    seq = xs[0]
    T = seq.shape[1]
    parts = [seq] + [jnp.broadcast_to(v[:, None],
                                      (v.shape[0], T) + v.shape[1:])
                     for v in xs[1:]]
    cat = jnp.concatenate(parts, axis=-1)
    out = jnp.einsum("btf,fd->btd", cat, w)
    if b is not None:
        out = out + b.reshape(1, 1, -1)
    return _act_by_name(fc_activation)(out), jnp.zeros((1,), seq.dtype)


@register_op("fusion_seqpool_concat", inputs=("X",), outputs=("Out",),
             attrs={"pooltype": "SUM", "axis": 1},
             duplicable_inputs=("X",))
def fusion_seqpool_concat(ctx, xs, pooltype="SUM", axis=1):
    """fusion_seqpool_concat_op.cc: sequence_pool each input, concat."""
    red = {"SUM": jnp.sum, "AVERAGE": jnp.mean,
           "SQRT": jnp.sum}[pooltype]
    pooled = []
    for x in xs:
        p = red(x, axis=1)
        if pooltype == "SQRT":
            p = p / jnp.sqrt(jnp.asarray(x.shape[1], x.dtype))
        pooled.append(p)
    return jnp.concatenate(pooled, axis=-1)


@register_op("fusion_seqpool_cvm_concat", inputs=("X", "CVM"),
             outputs=("Out",),
             attrs={"pooltype": "SUM", "use_cvm": True, "axis": 1},
             duplicable_inputs=("X",), no_grad_inputs=("CVM",))
def fusion_seqpool_cvm_concat(ctx, xs, cvm, pooltype="SUM", use_cvm=True,
                              axis=1):
    """fusion_seqpool_cvm_concat_op.cc: seqpool, then the CVM transform
    per pooled vector (cvm_op.cc: use_cvm=True rewrites the lead
    [show, click] columns to [log(show+1), log(click+1)-log(show+1)];
    use_cvm=False drops them), then concat."""
    from .detection2 import cvm as _cvm

    red = {"SUM": jnp.sum, "AVERAGE": jnp.mean, "SQRT": jnp.sum}[pooltype]
    pooled = []
    for x in xs:
        v = red(x, axis=1)
        if pooltype == "SQRT":
            v = v / jnp.sqrt(jnp.asarray(x.shape[1], x.dtype))
        pooled.append(_cvm(ctx, v, cvm, use_cvm=use_cvm))
    return jnp.concatenate(pooled, axis=-1)


@register_op("fusion_squared_mat_sub", inputs=("X", "Y"),
             outputs=("SquaredX", "SquaredY", "SquaredXY", "Out"),
             attrs={"scalar": 1.0})
def fusion_squared_mat_sub(ctx, x, y, scalar=1.0):
    """fusion_squared_mat_sub_op.cc: scalar * ((x@y)^2 - (x^2)@(y^2))."""
    xy = x @ y
    sx, sy = jnp.square(x), jnp.square(y)
    sxy = jnp.square(xy)
    return sx, sy, sxy, scalar * (sxy - sx @ sy)


@register_op("fusion_transpose_flatten_concat", inputs=("X",),
             outputs=("Out",),
             attrs={"trans_axis": [], "flatten_axis": 1,
                    "concat_axis": 1},
             duplicable_inputs=("X",))
def fusion_transpose_flatten_concat(ctx, xs, trans_axis=(),
                                    flatten_axis=1, concat_axis=1):
    """fusion_transpose_flatten_concat_op.cc."""
    outs = []
    for x in xs:
        t = jnp.transpose(x, trans_axis) if trans_axis else x
        lead = int(np.prod(t.shape[:flatten_axis]))
        outs.append(t.reshape(lead, -1))
    return jnp.concatenate(outs, axis=concat_axis)


@register_op("conv2d_fusion",
             inputs=("Input", "Filter", "Bias", "ResidualData"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "data_format": "NCHW", "activation": "relu",
                    "padding_algorithm": "EXPLICIT"},
             optional_inputs=("Bias", "ResidualData"))
def conv2d_fusion(ctx, x, w, bias, residual, strides=(1, 1),
                  paddings=(0, 0), dilations=(1, 1), groups=1,
                  data_format="NCHW", activation="relu", **_):
    """fused_conv2d (conv_fusion_op.cc): conv + bias + residual add +
    activation in one op (cuDNN fused path); XLA fuses the epilogue."""
    from .nn import conv2d

    out = conv2d(ctx, x, w, strides, paddings, dilations, groups,
                 data_format)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    if residual is not None:
        out = out + residual
    return _act_by_name(activation)(out)


@register_op("conv2d_inception_fusion",
             inputs=("Input", "Filter", "Bias"),
             outputs=("Output", "TempOutput"),
             attrs={"pooling_type": "max", "exclude_padding": True,
                    "activation": "relu"},
             duplicable_inputs=("Filter", "Bias"),
             duplicable_outputs=("TempOutput",))
def conv2d_inception_fusion(ctx, x, filters, biases, pooling_type="max",
                            exclude_padding=True, activation="relu"):
    """conv2d_inception_fusion_op.cc: the 4-branch inception block fused
    by cuDNN; composed here branch-by-branch (XLA fuses)."""
    from .nn import conv2d, pool2d

    act = _act_by_name(activation)
    branches = []
    tmp = []
    for w, b in zip(filters, biases):
        kh = w.shape[2]
        pad = (kh // 2, kh // 2)
        o = conv2d(ctx, x, w, (1, 1), pad, (1, 1), 1, "NCHW")
        o = act(o + b.reshape(1, -1, 1, 1))
        branches.append(o)
        tmp.append(o)
    p = pool2d(ctx, x, pooling_type, (3, 3), (1, 1), (1, 1),
               exclusive=exclude_padding)
    branches.append(p)
    return jnp.concatenate(branches, axis=1), tmp


# -- quantization tail -------------------------------------------------------


@register_op("fake_quantize_range_abs_max",
             inputs=("X", "InScale", "InScales", "Iter"),
             outputs=("Out", "OutScale", "OutScales"),
             attrs={"window_size": 10000, "bit_length": 8,
                    "is_test": False},
             optional_inputs=("InScales", "Iter"),
             no_grad_inputs=("InScale", "InScales", "Iter"))
def fake_quantize_range_abs_max(ctx, x, in_scale, in_scales, it,
                                window_size=10000, bit_length=8,
                                is_test=False):
    """fake_quantize_op.cc range_abs_max: WINDOWED max scale — the current
    abs-max is written into slot (Iter % window_size) of the scale history
    and the scale is the window maximum, so stale outliers age out (unlike
    a monotonic running max)."""
    from .quant import _quant_dequant

    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale.reshape(())
        hist = (in_scales if in_scales is not None
                else in_scale.reshape(1))
    else:
        if in_scales is not None:
            slot = (it.reshape(()).astype(jnp.int32) % window_size
                    if it is not None else 0)
            hist = in_scales.at[slot].set(cur)
            scale = jnp.max(hist)
        else:
            hist = cur.reshape(1)
            scale = jnp.maximum(cur, in_scale.reshape(()))
    return (_quant_dequant(x, scale, bit_length), scale.reshape(1), hist)


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=("X", "InScale", "InAccum", "InState"),
             outputs=("Out", "OutScale", "OutAccum", "OutState"),
             attrs={"moving_rate": 0.9, "bit_length": 8, "is_test": False},
             optional_inputs=("InAccum", "InState"),
             no_grad_inputs=("InScale", "InAccum", "InState"))
def fake_qd_moving_avg(ctx, x, in_scale, in_accum, in_state,
                       moving_rate=0.9, bit_length=8, is_test=False):
    from .quant import fake_quantize_moving_average_abs_max

    return fake_quantize_moving_average_abs_max(
        ctx, x, in_scale, in_accum, in_state, bit_length=bit_length,
        moving_rate=moving_rate, is_test=is_test)


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=("X", "Scales"),
             outputs=("Out",),
             attrs={"quant_bits": [8], "quant_axis": 0, "x_num_col_dims": 1},
             duplicable_inputs=("Scales",), grad_maker=None)
def fake_channel_wise_dequantize_max_abs(ctx, x, scales, quant_bits=(8,),
                                         quant_axis=0, x_num_col_dims=1):
    """fake_dequantize_op.cc channel-wise: x * scale / (2^bits-1)."""
    s = scales[0].reshape(-1)
    bnt = (1 << (int(quant_bits[0]) - 1)) - 1
    shape = [1] * x.ndim
    shape[quant_axis] = x.shape[quant_axis]
    out = x * s.reshape(shape) / bnt
    if len(scales) > 1:
        out = out * scales[1].reshape(()) / (
            (1 << (int(quant_bits[-1]) - 1)) - 1)
    return out


@register_op("dequantize_abs_max", inputs=("X", "Scale"),
             outputs=("Out",), attrs={"max_range": 127.0},
             grad_maker=None)
def dequantize_abs_max(ctx, x, scale, max_range=127.0):
    """dequantize_abs_max_op.cc: int8 -> float via scale/max_range."""
    return x.astype(jnp.float32) * scale.reshape(()) / max_range


@register_op("quantize", inputs=("Input",), outputs=("Output",),
             attrs={"Scale": 1.0, "is_negative_input": True,
                    "output_format": "NHWC"}, grad_maker=None)
def quantize(ctx, x, Scale=1.0, is_negative_input=True, **_):
    """mkldnn quantize_op.cc: float -> int8/uint8 by scale."""
    dt = jnp.int8 if is_negative_input else jnp.uint8
    return jnp.clip(jnp.round(x * Scale), -128 if is_negative_input else 0,
                    127 if is_negative_input else 255).astype(dt)


@register_op("dequantize", inputs=("Input",), outputs=("Output",),
             attrs={"Scale": 1.0}, grad_maker=None)
def dequantize(ctx, x, Scale=1.0, **_):
    """mkldnn dequantize_op.cc: int -> float by 1/scale."""
    return x.astype(jnp.float32) / Scale


@register_op("requantize", inputs=("Input",), outputs=("Output",),
             attrs={"Scale_in": 1.0, "Scale_out": 1.0}, grad_maker=None)
def requantize(ctx, x, Scale_in=1.0, Scale_out=1.0, **_):
    """mkldnn requantize_op.cc: rescale int8 data."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) * (Scale_out / Scale_in)),
                    -128, 127).astype(jnp.int8)


@register_op("moving_average_abs_max_scale",
             inputs=("X", "InAccum", "InState"),
             outputs=("Out", "OutScale", "OutAccum", "OutState"),
             attrs={"moving_rate": 0.9, "is_test": False},
             optional_inputs=("InAccum", "InState"),
             no_grad_inputs=("InAccum", "InState"))
def moving_average_abs_max_scale(ctx, x, in_accum, in_state,
                                 moving_rate=0.9, is_test=False):
    """fake_quantize_op.cc moving_average_abs_max_scale: observe-only op
    tracking the running abs-max (output passes x through)."""
    cur = jnp.max(jnp.abs(x))
    accum = in_accum.reshape(()) if in_accum is not None else jnp.asarray(
        0.0, x.dtype)
    state = in_state.reshape(()) if in_state is not None else jnp.asarray(
        0.0, x.dtype)
    new_state = moving_rate * state + 1.0
    new_accum = moving_rate * accum + cur
    scale = new_accum / new_state
    return x, scale.reshape(1), new_accum.reshape(1), new_state.reshape(1)


# -- model averaging ---------------------------------------------------------


@register_op("average_accumulates",
             inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                     "in_num_accumulates", "in_old_num_accumulates",
                     "in_num_updates"),
             outputs=("out_sum_1", "out_sum_2", "out_sum_3",
                      "out_num_accumulates", "out_old_num_accumulates",
                      "out_num_updates"),
             attrs={"average_window": 0.0, "max_average_window": 10000,
                    "min_average_window": 10000},
             grad_maker=None)
def average_accumulates(ctx, param, s1, s2, s3, na, ona, nu,
                        average_window=0.0, max_average_window=10000,
                        min_average_window=10000):
    """average_accumulates_op.cc (ModelAverage bookkeeping): rotate the
    three accumulator windows as updates stream in."""
    nu_new = nu + 1
    na_new = na + 1
    roll = (na_new >= min_average_window) & (
        na_new >= jnp.minimum(max_average_window,
                              nu_new * average_window).astype(na.dtype))
    s1n = jnp.where(roll, jnp.zeros_like(s1), s1 + param)
    s2n = jnp.where(roll, s1 + param, s2)
    s3n = jnp.where(roll, s2, s3)
    ona_new = jnp.where(roll, na_new, ona)
    na_out = jnp.where(roll, jnp.zeros_like(na_new), na_new)
    return s1n, s2n, s3n, na_out, ona_new, nu_new


# -- detection tail ----------------------------------------------------------


@register_op("mine_hard_examples",
             inputs=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"),
             outputs=("NegIndices", "UpdatedMatchIndices"),
             attrs={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                    "mining_type": "max_negative", "sample_size": 0},
             optional_inputs=("LocLoss",), grad_maker=None)
def mine_hard_examples(ctx, cls_loss, loc_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       mining_type="max_negative", sample_size=0):
    """mine_hard_examples_op.cc (SSD hard negative mining): per sample,
    mark the top-(neg_pos_ratio * num_pos) highest-loss negatives.  Static
    shapes: NegIndices is a [B, P] 0/1 mask over priors (padded analog of
    the reference's ragged index list)."""
    loss = cls_loss + (loc_loss if loc_loss is not None else 0.0)
    is_neg = match_indices < 0
    num_pos = jnp.sum(~is_neg, axis=1)
    num_neg = (num_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32)
    masked = jnp.where(is_neg, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)
    rank = jnp.argsort(order, axis=1)
    neg_mask = (rank < num_neg[:, None]) & is_neg
    upd = jnp.where(neg_mask, -1, match_indices)
    return neg_mask.astype(jnp.int32), upd


@register_op("detection_map",
             inputs=("DetectRes", "Label", "HasState", "PosCount",
                     "TruePos", "FalsePos"),
             outputs=("AccumPosCount", "AccumTruePos", "AccumFalsePos",
                      "MAP"),
             attrs={"overlap_threshold": 0.5, "evaluate_difficult": True,
                    "class_num": 1, "background_label": 0,
                    "ap_type": "integral"},
             optional_inputs=("HasState", "PosCount", "TruePos",
                              "FalsePos"),
             grad_maker=None)
def detection_map(ctx, det, label, has_state, pos_count, tp, fp,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  class_num=1, background_label=0, ap_type="integral"):
    """detection_map_op.cc mAP over padded detections.

    DetectRes [N, 6] rows are (label, score, xmin, ymin, xmax, ymax);
    Label rows are (label, xmin, ymin, xmax, ymax) or
    (label, difficult, xmin, ymin, xmax, ymax).  Per class: detections
    sorted by score greedily claim the best-IoU unmatched ground truth
    (one TP per gt, detection_map_op.h GetTpFpAccum analog); AP is
    integral or 11point; MAP averages classes with positives.  Rows with
    negative label are padding.  The streaming accumulators ride the
    returned slots (zeros when no incoming state)."""
    six_col = label.shape[1] >= 6
    gl = label[:, 0]
    gbox = label[:, 2:6] if six_col else label[:, 1:5]
    difficult = (label[:, 1] > 0.5) if six_col else jnp.zeros(
        label.shape[0], bool)
    gt_pad = gl < 0
    dl = det[:, 0]
    scores = det[:, 1]
    dbox = det[:, 2:6]
    det_pad = dl < 0

    def iou(a, b):
        ix = jnp.maximum(0.0, jnp.minimum(a[2], b[2])
                         - jnp.maximum(a[0], b[0]))
        iy = jnp.maximum(0.0, jnp.minimum(a[3], b[3])
                         - jnp.maximum(a[1], b[1]))
        inter = ix * iy
        ar_a = (a[2] - a[0]) * (a[3] - a[1])
        ar_b = (b[2] - b[0]) * (b[3] - b[1])
        return inter / jnp.maximum(ar_a + ar_b - inter, 1e-10)

    ious = jax.vmap(lambda d: jax.vmap(lambda g: iou(d, g))(gbox))(dbox)
    order = jnp.argsort(-scores)

    # One scan over score-sorted detections: matching is intra-class (the
    # candidate set is the unmatched gts of the DETECTION's class), so a
    # single class-agnostic pass yields every class's TP/FP stream at once
    # — no per-class unroll (class_num=81 COCO configs trace one scan).
    def step(used, d):
        cand = jnp.where((gl == dl[d]) & ~gt_pad & ~used, ious[d], -1.0)
        j = jnp.argmax(cand)
        hit = (~det_pad[d]) & (cand[j] >= overlap_threshold)
        if evaluate_difficult:
            tp_d = hit
        else:
            # a match to a difficult gt is ignored: not TP, not FP
            tp_d = hit & ~difficult[j]
        fp_d = (~det_pad[d]) & ~hit
        return used.at[j].set(used[j] | hit), (
            tp_d.astype(jnp.float32), fp_d.astype(jnp.float32))

    _, (tps, fps) = lax.scan(step, jnp.zeros(label.shape[0], bool), order)
    dl_sorted = dl[order]

    classes = jnp.arange(int(class_num))
    fg = classes != background_label                       # [C]
    in_c = (dl_sorted[None, :] == classes[:, None])        # [C, N]
    ctp = jnp.cumsum(tps[None, :] * in_c, axis=1)
    cfp = jnp.cumsum(fps[None, :] * in_c, axis=1)
    count_gt = ~gt_pad if evaluate_difficult else (~gt_pad & ~difficult)
    npos = jnp.sum((gl[None, :] == classes[:, None])
                   & count_gt[None, :], axis=1).astype(jnp.float32)  # [C]
    recall = ctp / jnp.maximum(npos[:, None], 1.0)
    precision = ctp / jnp.maximum(ctp + cfp, 1e-10)
    if ap_type == "11point":
        thresholds = jnp.asarray(np.arange(0.0, 1.01, 0.1), jnp.float32)
        pts = jnp.max(
            jnp.where(recall[:, None, :] >= thresholds[None, :, None],
                      precision[:, None, :], 0.0), axis=2)  # [C, 11]
        aps = jnp.sum(pts, axis=1) / 11.0
    else:
        prev = jnp.concatenate([jnp.zeros_like(recall[:, :1]),
                                recall[:, :-1]], axis=1)
        aps = jnp.sum((recall - prev) * precision, axis=1)
    w = fg.astype(jnp.float32) * (npos > 0).astype(jnp.float32)
    mean_ap = jnp.sum(aps * w) / jnp.maximum(jnp.sum(w), 1.0)
    z = jnp.zeros((1,), jnp.float32)
    return z, z, z, mean_ap.reshape(1)


@register_op("multiclass_nms2",
             inputs=("BBoxes", "Scores"),
             outputs=("Out", "Index"),
             attrs={"background_label": 0, "score_threshold": 0.0,
                    "nms_top_k": -1, "nms_threshold": 0.3, "nms_eta": 1.0,
                    "keep_top_k": -1, "normalized": True},
             grad_maker=None)
def multiclass_nms2(ctx, bboxes, scores, **attrs):
    """multiclass_nms2 (multiclass_nms_op.cc): nms + kept-index output."""
    from .detection import multiclass_nms

    if attrs.get("keep_top_k", -1) in (-1, 0):
        attrs["keep_top_k"] = 16
    if attrs.get("nms_top_k", -1) in (-1, 0):
        attrs["nms_top_k"] = 64
    out = multiclass_nms(ctx, bboxes, scores, **attrs)
    if isinstance(out, tuple):
        out = out[0]
    n = out.shape[0] if out.ndim else 1
    return out, jnp.arange(n, dtype=jnp.int32).reshape(-1, 1)


# -- executor / PS plumbing no-ops ------------------------------------------


@register_op("delete_var", inputs=("X",), outputs=(),
             duplicable_inputs=("X",), optional_inputs=("X",),
             grad_maker=None, stateful=True)
def delete_var(ctx, xs):
    """delete_var_op.cc: eager GC hint — XLA/PJRT owns buffer lifetime."""
    return ()


@register_op("fake_init", inputs=(), outputs=("Out",),
             attrs={"shape": [], "dtype": 5}, grad_maker=None)
def fake_init(ctx, shape=(), dtype=5):
    """fake_init_op.cc: PS-mode placeholder init (values come from the
    server); zeros keep the program runnable standalone."""
    from .common import attr_dtype

    return jnp.zeros([int(s) for s in shape], attr_dtype(dtype))


@register_op("coalesce_tensor", inputs=("Input",),
             outputs=("Output", "FusedOutput"),
             attrs={"copy_data": True, "set_constant": False,
                    "constant": 0.0, "dtype": 5},
             duplicable_inputs=("Input",), duplicable_outputs=("Output",),
             grad_maker=None)
def coalesce_tensor(ctx, xs, copy_data=True, set_constant=False,
                    constant=0.0, dtype=5):
    """coalesce_tensor_op.cc: pack tensors into one fused buffer (gradient
    bucketing).  XLA's allreduce combiner owns the packing on TPU; the op
    passes views through + emits the concatenated buffer."""
    fused = jnp.concatenate([x.reshape(-1) for x in xs])
    if set_constant:
        fused = jnp.full_like(fused, constant)
    return (list(xs), fused)


def _noop_plumbing(name, doc):
    @register_op(name, inputs=("X",), outputs=("Out",),
                 duplicable_inputs=("X",), duplicable_outputs=("Out",),
                 optional_inputs=("X",), grad_maker=None, stateful=True)
    def _op(ctx, xs):
        return (list(xs or []),)

    _op.__doc__ = doc
    return _op


# PS RPC ops: the runtime executes sends/recvs at the step boundary
# (core/executor.py ps_meta path; reference operators/distributed_ops/) —
# the ops exist so transpiled reference programs load and run.
for _name in ("send", "recv", "send_barrier", "fetch_barrier", "prefetch",
              "checkpoint_notify"):
    _noop_plumbing(_name, "distributed_ops/%s_op.cc: handled by the "
                          "runtime PS communicator at step boundaries" % _name)


@register_op("conditional_block_infer", inputs=("Cond", "Input"),
             outputs=("Out", "Scope"),
             attrs={"sub_block": -1, "is_scalar_condition": True},
             duplicable_inputs=("Cond", "Input"),
             duplicable_outputs=("Out",), optional_inputs=("Input",),
             grad_maker=None, stateful=True)
def conditional_block_infer(ctx, conds, inputs, sub_block=-1,
                            is_scalar_condition=True, **_):
    """conditional_block_infer_op.cc: inference variant — same lowering."""
    from .control_flow import conditional_block

    return conditional_block(ctx, conds, inputs, sub_block,
                             is_scalar_condition)


# save/load combine: io.py gathers/scatters directly; the ops exist so
# reference save-programs execute (operators/save_combine_op.cc).


@register_op("save_combine", inputs=("X",), outputs=(),
             attrs={"file_path": "", "overwrite": True,
                    "save_as_fp16": False},
             duplicable_inputs=("X",), grad_maker=None, stateful=True)
def save_combine(ctx, xs, file_path="", overwrite=True,
                 save_as_fp16=False):
    """save_combine_op.cc: write the inputs as one legacy-format stream
    (proto_compat LoDTensor records, sorted caller-side)."""
    import jax

    def _save(*arrs):
        from .. import proto_compat

        with open(file_path, "wb") as f:
            for a in arrs:
                proto_compat.write_lod_tensor(f, np.asarray(a))

    jax.debug.callback(_save, *xs)
    return ()


@register_op("load_combine", inputs=(), outputs=("Out",),
             attrs={"file_path": "", "load_as_fp16": False,
                    "model_from_memory": False},
             duplicable_outputs=("Out",), grad_maker=None, stateful=True)
def load_combine(ctx, file_path="", **_):
    """load_combine_op.cc: read a legacy combined stream.  Host-side read
    at trace time (shapes must be static)."""
    from .. import proto_compat

    arrs = []
    with open(file_path, "rb") as f:
        while True:
            try:
                a, _lod = proto_compat.read_lod_tensor(f)
            except Exception:
                break
            arrs.append(jnp.asarray(a))
    return (arrs,)
