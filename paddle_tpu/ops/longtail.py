"""Long-tail forward ops completing REGISTER_OPERATOR parity.

Covers the remaining reference operators (paddle/fluid/operators/):
minus_op.cc, hinge_loss_op.cc, modified_huber_loss_op.cc,
squared_l2_distance_op.cc, conv_shift_op.cc, unpool_op.cc, spp_op.cc,
sample_logits_op.cc, select_input_op.cc, select_output_op.cc,
get_tensor_from_selected_rows_op.cc, pull_box_sparse_op.cc /
push_box_sparse, pyramid_hash_op.cc, var_conv_2d_op.cc, tree_conv_op.cc,
attention_lstm_op.cc.

Sequence-shaped inputs follow the repo's padded design (ops/sequence.py):
dense [B, T, ...] + optional per-row Length instead of LoD offsets.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op

# -- simple math / loss ------------------------------------------------------


@register_op("minus", inputs=("X", "Y"), outputs=("Out",))
def minus(ctx, x, y):
    """minus_op.cc: Out = X - Y."""
    return x - y


@register_op("hinge_loss", inputs=("Logits", "Labels"), outputs=("Loss",),
             no_grad_inputs=("Labels",))
def hinge_loss(ctx, logits, labels):
    """hinge_loss_op.h: L = max(0, 1 - (2*label - 1) * pred)."""
    y = 2.0 * labels.astype(logits.dtype) - 1.0
    return jnp.maximum(0.0, 1.0 - y * logits)


@register_op("modified_huber_loss", inputs=("X", "Y"),
             outputs=("IntermediateVal", "Out"), no_grad_inputs=("Y",))
def modified_huber_loss(ctx, x, y):
    """modified_huber_loss_op.h: with a = (2y-1)*x:
    loss = (max(0, 1-a))^2 if a >= -1 else -4a."""
    a = (2.0 * y.astype(x.dtype) - 1.0) * x
    quad = jnp.square(jnp.maximum(0.0, 1.0 - a))
    lin = -4.0 * a
    return a, jnp.where(a >= -1.0, quad, lin)


@register_op("squared_l2_distance", inputs=("X", "Y"),
             outputs=("sub_result", "Out"))
def squared_l2_distance(ctx, x, y):
    """squared_l2_distance_op.h: sub = x - y (y row-broadcast when its
    batch is 1); Out[i] = sum(sub[i]^2)."""
    sub = x - y
    out = jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                  keepdims=False).reshape(-1, 1)
    return sub, out


@register_op("conv_shift", inputs=("X", "Y"), outputs=("Out",))
def conv_shift(ctx, x, y):
    """conv_shift_op.cc circular convolution: X [B, W], Y [B, K] (K odd,
    K <= W): Out[b, i] = sum_k X[b, (i + k - K/2) mod W] * Y[b, k]."""
    W = x.shape[1]
    K = y.shape[1]
    half = K // 2
    # gather shifted views: index matrix [W, K]
    idx = (jnp.arange(W)[:, None] + jnp.arange(K)[None, :] - half) % W
    xg = x[:, idx]  # [B, W, K]
    return jnp.einsum("bwk,bk->bw", xg, y)


# -- pooling-family ----------------------------------------------------------


@register_op("unpool", inputs=("X", "Indices"), outputs=("Out",),
             attrs={"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                    "unpooling_type": "max"},
             no_grad_inputs=("Indices",))
def unpool(ctx, x, indices, ksize=(2, 2), strides=(2, 2), paddings=(0, 0),
           unpooling_type="max"):
    """unpool_op.cc: max-unpooling. X/Indices [N, C, H, W]; Indices hold
    flat positions (h*W_out + w) into the output spatial plane (as produced
    by max_pool2d_with_index); output [N, C, H_out, W_out] scatters X
    values to those positions."""
    n, c, h, w = x.shape
    hout = (h - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    wout = (w - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n, c, hout * wout), x.dtype)
    idx = indices.reshape(n, c, h * w).astype(jnp.int32)
    vals = x.reshape(n, c, h * w)
    bidx = jnp.arange(n)[:, None, None]
    cidx = jnp.arange(c)[None, :, None]
    flat = flat.at[bidx, cidx, idx].add(vals)
    return flat.reshape(n, c, hout, wout)


@register_op("spp", inputs=("X",), outputs=("Out",),
             attrs={"pyramid_height": 2, "pooling_type": "max"})
def spp(ctx, x, pyramid_height=2, pooling_type="max"):
    """spp_op.cc spatial pyramid pooling: for level p in [0, height), pool
    X [N,C,H,W] into a 2^p x 2^p grid (adaptive kernel), flatten, concat
    along channels -> [N, C * sum(4^p)]."""
    n, c, h, w = x.shape
    outs = []
    for p in range(pyramid_height):
        bins = 2 ** p
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        if pooling_type == "max":
            init = -jnp.inf
            xp = jnp.pad(x, ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                             (pw, kw * bins - w - pw)),
                         constant_values=-np.inf)
            pooled = lax.reduce_window(
                xp, init, lax.max, (1, 1, kh, kw), (1, 1, kh, kw),
                "VALID")
        else:
            xp = jnp.pad(x, ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                             (pw, kw * bins - w - pw)))
            s = lax.reduce_window(xp, 0.0, lax.add, (1, 1, kh, kw),
                                  (1, 1, kh, kw), "VALID")
            cnt = lax.reduce_window(
                jnp.pad(jnp.ones_like(x),
                        ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                         (pw, kw * bins - w - pw))),
                0.0, lax.add, (1, 1, kh, kw), (1, 1, kh, kw), "VALID")
            pooled = s / jnp.maximum(cnt, 1.0)
        outs.append(pooled.reshape(n, -1))
    return jnp.concatenate(outs, axis=1)


# -- sampled softmax ---------------------------------------------------------


@register_op("sample_logits",
             inputs=("Logits", "Labels", "CustomizedSamples",
                     "CustomizedProbabilities"),
             outputs=("Samples", "Probabilities", "LogitsDim", "LabelsDim",
                      "SampledLogits", "SampledLabels"),
             attrs={"use_customized_samples": False, "uniq": True,
                    "remove_accidental_hits": True, "num_samples": 1,
                    "seed": 0},
             optional_inputs=("CustomizedSamples", "CustomizedProbabilities"),
             no_grad_inputs=("Labels", "CustomizedSamples",
                             "CustomizedProbabilities"),
             n_rng=1)
def sample_logits(ctx, logits, labels, cust_samples, cust_probs,
                  use_customized_samples=False, uniq=True,
                  remove_accidental_hits=True, num_samples=1, seed=0, **_):
    """sample_logits_op.cc: sampled-softmax helper.  Gathers true-label
    logits plus `num_samples` uniformly sampled negative classes; sampled
    logits are corrected by -log(prob) (uniform sampler; the reference's
    CPU kernel uses the same uniform sampler, sample_logits_op.h)."""
    B, C = logits.shape
    NT = labels.shape[1]
    if use_customized_samples and cust_samples is not None:
        samples = cust_samples
        probs = cust_probs
    else:
        key = ctx.rng() if ctx is not None else jax.random.PRNGKey(seed)
        neg = jax.random.randint(key, (B, num_samples), 0, C)
        samples = jnp.concatenate([labels.astype(jnp.int64),
                                   neg.astype(jnp.int64)], axis=1)
        p = jnp.full(samples.shape, 1.0 / C, logits.dtype)
        probs = p
    sampled = jnp.take_along_axis(logits, samples.astype(jnp.int32), axis=1)
    sampled = sampled - jnp.log(probs.astype(logits.dtype))
    if remove_accidental_hits:
        # negatives equal to any true label get -inf-ish logits
        neg_part = samples[:, NT:]
        hit = (neg_part[:, :, None] ==
               labels[:, None, :].astype(samples.dtype)).any(axis=2)
        penal = jnp.where(hit, jnp.asarray(-1e20, sampled.dtype), 0.0)
        sampled = sampled.at[:, NT:].add(penal)
    sampled_labels = jnp.tile(jnp.arange(NT, dtype=jnp.int64)[None, :],
                              (B, 1))
    return (samples, probs, jnp.zeros((2,), jnp.int64),
            jnp.zeros((2,), jnp.int64), sampled, sampled_labels)


# -- control-flow selection --------------------------------------------------


@register_op("select_input", inputs=("X", "Mask"), outputs=("Out",),
             duplicable_inputs=("X",), no_grad_inputs=("Mask",))
def select_input(ctx, xs, mask):
    """select_input_op.cc: Out = X[Mask] (Mask is a 1-element int tensor).
    Differentiable in each branch (the reference's grad is select_output)."""
    m = mask.reshape(()).astype(jnp.int32)
    if len(xs) == 1:
        return xs[0]
    return lax.switch(jnp.clip(m, 0, len(xs) - 1),
                      [lambda *_a, i=i: xs[i] for i in range(len(xs))])


@register_op("select_output", inputs=("X", "Mask"), outputs=("Out",),
             duplicable_outputs=("Out",), no_grad_inputs=("Mask",))
def select_output(ctx, x, mask):
    """select_output_op.cc: route X to Out[Mask]; unselected outputs are
    zeros (the reference leaves them uninitialized — zeros is the
    compiled-graph-safe equivalent and matches its use as select_input's
    gradient)."""
    op = ctx.op if ctx is not None else None
    n = len(op.output("Out")) if op is not None else 1
    m = mask.reshape(()).astype(jnp.int32)
    outs = [jnp.where(m == i, x, jnp.zeros_like(x)) for i in range(n)]
    return (outs,)


@register_op("get_tensor_from_selected_rows", inputs=("X",),
             outputs=("Out",))
def get_tensor_from_selected_rows(ctx, x):
    """get_tensor_from_selected_rows_op.cc: densify a SelectedRows.  Sparse
    row-sets are carried dense in this framework (SelectedRows dissolve to
    dense gradients under XLA), so this is the identity on the values."""
    return x


# -- sparse-embedding family -------------------------------------------------


@register_op("pull_box_sparse", inputs=("Ids", "W"), outputs=("Out",),
             duplicable_inputs=("Ids",), duplicable_outputs=("Out",),
             attrs={"size": 1}, no_grad_inputs=("Ids",))
def pull_box_sparse(ctx, ids_list, w, size=1):
    """pull_box_sparse_op.cc: batched embedding pulls.  The reference pulls
    from the external BoxPS service; here the table rides as a dense W
    [rows, size] parameter (the PS-backed path is distributed_lookup_table)
    and each Ids tensor gathers its rows."""
    outs = []
    for ids in ids_list:
        flat = ids.reshape(-1).astype(jnp.int32)
        outs.append(jnp.take(w, flat, axis=0).reshape(
            tuple(ids.shape[:-1]) + (w.shape[-1],)))
    return (outs,)


@register_op("push_box_sparse", inputs=("Ids", "Out@GRAD"), outputs=(),
             duplicable_inputs=("Ids", "Out@GRAD"), attrs={"size": 1},
             grad_maker=None)
def push_box_sparse(ctx, ids_list, grads, size=1):
    """push_box_sparse (pull_box_sparse_op.cc): gradient push is handled by
    the autodiff of pull_box_sparse in this framework; the op exists for
    program parity and is a no-op."""
    return ()


@register_op("pyramid_hash", inputs=("X", "W", "WhiteList", "BlackList"),
             outputs=("Out", "DropPos", "X_Temp_Out"),
             attrs={"num_emb": 0, "space_len": 0, "pyramid_layer": 2,
                    "rand_len": 16, "drop_out_percent": 0.0,
                    "is_training": 0, "use_filter": True,
                    "white_list_len": 0, "black_list_len": 0, "seed": 0,
                    "lr": 0.0},
             optional_inputs=("WhiteList", "BlackList"),
             no_grad_inputs=("X", "WhiteList", "BlackList"))
def pyramid_hash(ctx, x, w, white, black, num_emb=0, space_len=0,
                 pyramid_layer=2, rand_len=16, **_):
    """pyramid_hash_op.cc (PyramidDNN): hash every n-gram (n in
    [2, pyramid_layer]) of the token-id sequence into rows of W and sum
    their embeddings.  X here is the padded [B, T] id matrix (the reference
    uses a LoD row of ids); the hash is a cheap deterministic mix instead
    of xxhash — same structure, table-size-modular."""
    num_emb = num_emb or w.shape[-1]
    B, T = x.shape[0], x.shape[1]
    ids = x.reshape(B, T).astype(jnp.uint32)
    rows = jnp.uint32(w.shape[0])
    total = jnp.zeros((B, num_emb), w.dtype)
    for n in range(2, pyramid_layer + 1):
        if T < n:
            break
        h = jnp.zeros((B, T - n + 1), jnp.uint32)
        for k in range(n):
            h = h * jnp.uint32(1000003) + ids[:, k:T - n + 1 + k]
        idx = (h % rows).astype(jnp.int32)
        emb = jnp.take(w, idx.reshape(-1), axis=0).reshape(
            B, -1, w.shape[-1])
        total = total + jnp.sum(emb, axis=1)[:, :num_emb]
    return total, jnp.zeros((1,), jnp.int64), jnp.zeros((1,), jnp.int64)


# -- structured convs --------------------------------------------------------


@register_op("var_conv_2d", inputs=("X", "ROW", "COLUMN", "W"),
             outputs=("Out", "Col"),
             attrs={"InputChannel": 1, "OutputChannel": 1, "StrideH": 1,
                    "StrideW": 1, "KernelH": 1, "KernelW": 1},
             optional_inputs=("ROW", "COLUMN"),
             no_grad_inputs=("ROW", "COLUMN"))
def var_conv_2d(ctx, x, row, column, w, InputChannel=1, OutputChannel=1,
                StrideH=1, StrideW=1, KernelH=1, KernelW=1):
    """var_conv_2d_op.cc: per-sample variable-size 2d conv.  Padded design:
    X is a dense [B, InputChannel, H, W] batch (the ragged per-sample sizes
    of the reference become padding; ROW/COLUMN length hints are accepted
    for API parity).  W is [OutputChannel, InputChannel*KernelH*KernelW]."""
    B = x.shape[0]
    wf = w.reshape(OutputChannel, InputChannel, KernelH, KernelW)
    out = lax.conv_general_dilated(
        x, wf, window_strides=(StrideH, StrideW),
        padding=[(KernelH // 2, KernelH // 2), (KernelW // 2, KernelW // 2)],
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, wf.shape, ("NCHW", "OIHW", "NCHW")))
    return out, jnp.zeros((1,), x.dtype)


@register_op("tree_conv", inputs=("NodesVector", "EdgeSet", "Filter"),
             outputs=("Out",), attrs={"max_depth": 2},
             no_grad_inputs=("EdgeSet",))
def tree_conv(ctx, nodes, edges, filt, max_depth=2):
    """tree_conv_op.h (tree-based convolution, TBCNN) with the reference
    Tree2Col semantics EXACTLY (math/tree2col.cc):

    NodesVector [B, N, F]; EdgeSet [B, E, 2] of 1-BASED (parent, child)
    pairs — a pair containing 0 terminates the edge list (tree2col.cc
    construct_tree); Filter [F, 3, out, filters].  Each node u collects
    its descendants v with dist(u, v) < max_depth; v contributes its
    feature vector to three positional slots weighted by (tree2col.h
    TreeNode):

        eta_t = (D - depth) / D                      (D = max_depth)
        temp  = 0.5 if pclen == 1 else (index-1)/(pclen-1)
        eta_l = (1 - eta_t) * temp
        eta_r = (1 - eta_t) * (1 - eta_l)            # NB: full eta_l

    where (index, pclen) are v's 1-based position among its parent's
    children and the child count — except the patch ROOT uses
    (index=1, pclen=1, depth=0).  Vectorized as a static max_depth walk
    up parent chains with scatter-adds (a lax-friendly emission of the
    reference's DFS patch construction; exact for trees, the op's
    contract)."""
    B, N, F = nodes.shape
    E = edges.shape[1]
    D = float(max_depth)
    e = edges.astype(jnp.int32)
    parent_e, child_e = e[:, :, 0], e[:, :, 1]  # [B, E], 1-based
    # the reference STOPS at the first pair containing a zero
    ok = (parent_e != 0) & (child_e != 0)
    valid = jnp.cumprod(ok.astype(jnp.int32), axis=1).astype(bool)

    # per-node parent (1-based; 0 = none/root), child index (1-based)
    # and parent's child count, from edge order (tr[u].push_back(v))
    parent = jnp.zeros((B, N + 1), jnp.int32)
    child_safe = jnp.where(valid, child_e, 0)
    bidx = jnp.arange(B)[:, None]
    parent = parent.at[bidx, child_safe].set(
        jnp.where(valid, parent_e, 0), mode="drop")
    # index of v within its parent's list = 1 + #earlier edges with the
    # same parent
    same_parent = (parent_e[:, None, :] == parent_e[:, :, None]) & \
        valid[:, None, :] & valid[:, :, None]
    earlier = jnp.tril(jnp.ones((E, E), bool), k=-1)[None]
    index_e = 1 + jnp.sum(same_parent & earlier, axis=2)  # [B, E]
    pclen_e = jnp.sum(same_parent, axis=2)                # [B, E]
    index = jnp.zeros((B, N + 1), jnp.int32).at[bidx, child_safe].set(
        jnp.where(valid, index_e, 0), mode="drop")
    pclen = jnp.zeros((B, N + 1), jnp.int32).at[bidx, child_safe].set(
        jnp.where(valid, pclen_e, 0), mode="drop")

    # node_count: nodes 1..node_count have patches (reference:
    # #valid edges + 1)
    node_count = jnp.sum(valid, axis=1) + 1  # [B]
    node_ids = jnp.arange(1, N + 1)[None, :]  # [B, N] candidate v
    exists = node_ids <= node_count[:, None]

    def etas(idx, pcl, depth):
        idx = idx.astype(jnp.float32)
        pcl = pcl.astype(jnp.float32)
        eta_t = jnp.full_like(idx, (D - depth) / D)
        temp = jnp.where(pcl == 1, 0.5,
                         (idx - 1.0) / jnp.maximum(pcl - 1.0, 1.0))
        eta_l = (1.0 - eta_t) * temp
        eta_r = (1.0 - eta_t) * (1.0 - eta_l)
        return eta_l, eta_r, eta_t

    feats = nodes  # features[id-1] = nodes[:, id-1]
    patch = jnp.zeros((B, N, 3, F), nodes.dtype)
    anc = node_ids  # ancestor at distance k (1-based; 0 = none)
    for k in range(max_depth):
        if k == 0:
            el, er, et = etas(jnp.ones_like(node_ids),
                              jnp.ones_like(node_ids), 0.0)
        else:
            anc = jnp.where(anc > 0,
                            jnp.take_along_axis(
                                parent, jnp.maximum(anc, 0), axis=1), 0)
            el, er, et = etas(
                jnp.take_along_axis(index, node_ids, axis=1),
                jnp.take_along_axis(pclen, node_ids, axis=1), float(k))
        contrib_ok = (anc > 0) & exists
        w = jnp.stack([el, er, et], axis=-1).astype(nodes.dtype)  # [B,N,3]
        vals = jnp.where(contrib_ok[..., None, None],
                         w[..., :, None] * feats[:, :, None, :], 0.0)
        rows = jnp.where(contrib_ok, anc - 1, N)  # N = dropped
        patch = patch.at[bidx, rows].add(vals, mode="drop")
    # patch slots interleave per feature as (l, r, t) — i*3 + slot — and
    # W flattens [F, 3] row-major the same way, so einsum over (f, slot)
    out = jnp.einsum("bnsf,fsom->bnom", patch, filt)
    out = jnp.where(exists[:, :, None, None], out, 0.0)
    return out.reshape(B, N, -1)


# -- fused attention LSTM ----------------------------------------------------


def _act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}[name]


@register_op("attention_lstm",
             inputs=("X", "C0", "H0", "AttentionWeight", "AttentionBias",
                     "AttentionScalar", "AttentionScalarBias", "LSTMWeight",
                     "LSTMBias", "Length"),
             outputs=("Hidden", "Cell", "AttentionedX", "AttentionFCOut",
                      "LSTMX", "LSTMOUT"),
             attrs={"gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"},
             optional_inputs=("H0", "AttentionBias", "AttentionScalar",
                              "AttentionScalarBias", "Length"),
             no_grad_inputs=("Length",))
def attention_lstm(ctx, x, c0, h0, atten_w, atten_b, atten_scalar,
                   atten_scalar_bias, lstm_w, lstm_b, length,
                   gate_activation="sigmoid", cell_activation="tanh",
                   candidate_activation="tanh"):
    """attention_lstm_op.cc, padded layout: X [B, T, M] (+ optional Length
    [B]); C0/H0 [B, D]; AttentionWeight [(M+D), 1]; LSTMWeight [(D+M), 4D]
    with gate order {forget, input, output, candidate} (rows: first D for
    h, next M for x — attention_lstm_op.cc:380-385); per step the attention
    scores relu(x@w_x + c_prev.w_c [+bias]) [optional scalar+relu] are
    softmaxed over the (valid) source steps and pool X into the LSTM input
    (op comment, attention_lstm_op.cc:222-232)."""
    act_gate = _act(gate_activation)
    act_cell = _act(cell_activation)
    act_cand = _act(candidate_activation)
    B, T, M = x.shape
    D = lstm_w.shape[1] // 4
    w_x, w_c = atten_w[:M, :], atten_w[M:, :]
    atted_x = jnp.einsum("btm,mo->bto", x, w_x)[..., 0]  # [B, T]
    if atten_b is not None:
        atted_x = atted_x + atten_b.reshape(())
    if length is not None:
        valid = (jnp.arange(T)[None, :] <
                 length.reshape(-1, 1)).astype(x.dtype)
    else:
        valid = jnp.ones((B, T), x.dtype)
    h0_ = jnp.zeros((B, D), x.dtype) if h0 is None else h0
    w_h, w_xx = lstm_w[:D, :], lstm_w[D:, :]

    def step(carry, t):
        h_prev, c_prev = carry
        score = atted_x + (c_prev @ w_c).reshape(B, 1)  # [B, T]
        score = jax.nn.relu(score)
        if atten_scalar is not None:
            score = score * atten_scalar.reshape(())
            if atten_scalar_bias is not None:
                score = score + atten_scalar_bias.reshape(())
            score = jax.nn.relu(score)
        score = jnp.where(valid > 0, score, -1e30)
        attn = jax.nn.softmax(score, axis=1)
        lstm_x = jnp.einsum("bt,btm->bm", attn, x)
        gates = lstm_x @ w_xx + h_prev @ w_h + lstm_b.reshape(-1)
        f = act_gate(gates[:, :D])
        i = act_gate(gates[:, D:2 * D])
        o = act_gate(gates[:, 2 * D:3 * D])
        cand = act_cand(gates[:, 3 * D:])
        c_t = f * c_prev + i * cand
        h_t = o * act_cell(c_t)
        on = valid[:, t].reshape(B, 1)
        c_t = jnp.where(on > 0, c_t, c_prev)
        h_t = jnp.where(on > 0, h_t, h_prev)
        return (h_t, c_t), (h_t * on, c_t * on)

    (_hf, _cf), (hs, cs) = lax.scan(step, (h0_, c0), jnp.arange(T))
    hidden = jnp.swapaxes(hs, 0, 1)  # [B, T, D]
    cell = jnp.swapaxes(cs, 0, 1)
    z1 = jnp.zeros((T, 1), x.dtype)
    return (hidden, cell, atted_x, z1, jnp.zeros((1, M), x.dtype),
            jnp.zeros((1, 4 * D), x.dtype))
