"""Operator library: registrations of all op lowerings.

Importing this package populates the registry (analog of the reference's
static REGISTER_OPERATOR initializers, op_registry.h:199).
"""

from . import creation  # noqa: F401
from . import math  # noqa: F401
from . import activations  # noqa: F401
from . import loss  # noqa: F401
from . import manip  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import metrics  # noqa: F401
from . import collective  # noqa: F401
from . import control_flow  # noqa: F401
from . import sequence  # noqa: F401
from . import beam_search  # noqa: F401
from . import vision  # noqa: F401
from . import detection  # noqa: F401
from . import loss_extra  # noqa: F401
from . import misc2  # noqa: F401
from . import crf  # noqa: F401
from . import sampled  # noqa: F401
from . import quant  # noqa: F401
from . import misc3  # noqa: F401
from . import detection2  # noqa: F401
from . import longtail  # noqa: F401
from . import coverage_tail  # noqa: F401
from . import contrib_rnn  # noqa: F401
