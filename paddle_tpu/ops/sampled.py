"""Sampled / hierarchical classification ops + host callback.

Parity (paddle/fluid/operators/): nce_op.cc (noise contrastive estimation,
uniform sampler), hierarchical_sigmoid_op.cc (SimpleCode complete binary
tree, matrix_bit_code.h), py_func_op.cc (host Python callback — lowered via
jax.pure_callback instead of holding the GIL inside an op kernel).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("nce", inputs=("Input", "Label", "Weight", "Bias",
                            "SampleWeight", "CustomDistProbs",
                            "CustomDistAlias", "CustomDistAliasProbs"),
             outputs=("Cost", "SampleLogits", "SampleLabels"),
             attrs={"num_total_classes": 2, "num_neg_samples": 10,
                    "seed": 0, "sampler": 0, "is_sparse": False},
             optional_inputs=("Bias", "SampleWeight", "CustomDistProbs",
                              "CustomDistAlias", "CustomDistAliasProbs"),
             no_grad_inputs=("Label", "SampleWeight", "CustomDistProbs",
                             "CustomDistAlias", "CustomDistAliasProbs"),
             n_rng=1)
def nce(ctx, x, label, weight, bias=None, sample_weight=None,
        custom_probs=None, custom_alias=None, custom_alias_probs=None,
        num_total_classes=2, num_neg_samples=10, seed=0, sampler=0,
        is_sparse=False, **_):
    """NCE loss (nce_op.cc): x [B, D], label [B, 1], weight [C, D],
    bias [C].  Samplers (nce_op.h + math/sampler.cc): 0=uniform,
    1=log_uniform (Zipfian, inverse-CDF draw), 2=custom_dist
    (CustomDistProbs [C]; drawn with jax.random.categorical — the
    reference's alias tables are a CPU-side speedup for the same
    distribution, so Alias/AliasProbs are accepted and unused)."""
    B = x.shape[0]
    C = num_total_classes
    lbl = label.reshape(-1).astype(jnp.int32)
    key = ctx.rng()
    if sampler == 1:
        # P(k) = (log(k+2) - log(k+1)) / log(C+1); inverse CDF of
        # F(k) = log(k+2)/log(C+1) from u~U(0,1): k = floor((C+1)^u) - 1
        u = jax.random.uniform(key, (B, num_neg_samples))
        neg = jnp.clip(
            jnp.floor(jnp.exp(u * jnp.log(float(C + 1)))) - 1.0,
            0, C - 1).astype(jnp.int32)

        def log_q(ids):
            idf = ids.astype(jnp.float32)
            return jnp.log((jnp.log(idf + 2.0) - jnp.log(idf + 1.0))
                           / jnp.log(float(C + 1)))
    elif sampler == 2:
        probs = custom_probs.reshape(-1).astype(jnp.float32)
        logits_dist = jnp.log(jnp.maximum(probs, 1e-30))
        neg = jax.random.categorical(
            key, logits_dist, shape=(B, num_neg_samples)).astype(jnp.int32)

        def log_q(ids):
            return jnp.log(jnp.maximum(probs[ids], 1e-30))
    else:
        neg = jax.random.randint(key, (B, num_neg_samples), 0, C)

        def log_q(ids):
            return jnp.full(ids.shape, -jnp.log(float(C)))

    def logit(ids):
        w = weight[ids]                       # [..., D]
        out = jnp.sum(w * x[:, None, :] if ids.ndim == 2 else w * x, axis=-1)
        if bias is not None:
            out = out + bias[ids]
        return out

    pos_logit = logit(lbl)                    # [B]
    neg_logit = logit(neg)                    # [B, S]
    s = float(num_neg_samples)
    pos = jax.nn.log_sigmoid(pos_logit - jnp.log(s) - log_q(lbl))
    neg_ = jax.nn.log_sigmoid(-(neg_logit - jnp.log(s) - log_q(neg)))
    cost = -(pos + jnp.sum(neg_, axis=1))
    sample_logits = jnp.concatenate([pos_logit[:, None], neg_logit], axis=1)
    sample_labels = jnp.concatenate([lbl[:, None], neg], axis=1)
    return cost[:, None], sample_logits, sample_labels.astype(jnp.int64)


@register_op("hierarchical_sigmoid",
             inputs=("X", "W", "Label", "PathTable", "PathCode", "Bias"),
             outputs=("Out", "PreOut", "W_Out"),
             attrs={"num_classes": 2, "is_sparse": False},
             optional_inputs=("PathTable", "PathCode", "Bias"),
             no_grad_inputs=("Label", "PathTable", "PathCode"))
def hierarchical_sigmoid(ctx, x, w, label, path_table=None, path_code=None,
                         bias=None, num_classes=2, is_sparse=False, **_):
    """Hierarchical sigmoid over the SimpleCode complete binary tree
    (hierarchical_sigmoid_op.cc + matrix_bit_code.h): code(c) = c + C;
    path node i = (code >> (len-i)) - 1, bit i = (code >> (len-1-i)) & 1.
    x [B, D], w [C-1+pad, D], label [B, 1]."""
    import math

    B, D = x.shape
    C = num_classes
    max_len = max(int(math.floor(math.log2(max(C, 2)))) + 1, 1)
    lbl = label.reshape(-1).astype(jnp.int32)
    code = lbl + C
    # length = floor(log2(code)); compute via comparisons (static max_len)
    length = jnp.zeros_like(code)
    for k in range(1, max_len + 2):
        length = jnp.where(code >= (1 << k), k, length)
    steps = jnp.arange(max_len)[None, :]                       # [1, L]
    valid = steps < length[:, None]
    node = jnp.where(valid, (code[:, None] >> (length[:, None] - steps)) - 1,
                     0)
    bit = jnp.where(valid,
                    (code[:, None] >> (length[:, None] - 1 - steps)) & 1, 0)
    wn = w[node]                                               # [B, L, D]
    pre = jnp.einsum("bld,bd->bl", wn, x)
    if bias is not None:
        pre = pre + bias.reshape(-1)[node]
    # label bit 1 -> sigmoid(pre), 0 -> 1 - sigmoid(pre); NLL sum over path
    sign = 1.0 - 2.0 * bit.astype(pre.dtype)
    losses = jnp.logaddexp(0.0, sign * pre)
    loss = jnp.sum(jnp.where(valid, losses, 0.0), axis=1)
    return loss[:, None], pre, w


_PYFUNC_REGISTRY = {}


def register_py_func(fn):
    """Register a host callback; returns its id (py_func_op.cc's
    py_func registry analog)."""
    fid = len(_PYFUNC_REGISTRY)
    _PYFUNC_REGISTRY[fid] = fn
    return fid


@register_op("py_func", inputs=("X",), outputs=("Out",),
             attrs={"forward_callable_id": 0, "backward_callable_id": -1,
                    "out_shapes": [], "out_dtypes": []},
             duplicable_inputs=("X",), duplicable_outputs=("Out",),
             grad_maker=None)
def py_func(ctx, xs, forward_callable_id=0, backward_callable_id=-1,
            out_shapes=(), out_dtypes=()):
    """Host Python callback inside a compiled program via
    jax.pure_callback (py_func_op.cc analog; the callback must be
    functionally pure — it runs outside the XLA graph on the host)."""
    import numpy as np

    fn = _PYFUNC_REGISTRY[forward_callable_id]
    shapes = [tuple(s) for s in out_shapes]
    dtypes = [np.dtype(d) for d in out_dtypes]
    result_shape = [jax.ShapeDtypeStruct(s, d)
                    for s, d in zip(shapes, dtypes)]

    def host_fn(*arrays):
        out = fn(*arrays)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(np.asarray(o, dtype=d)
                     for o, d in zip(out, dtypes))

    out = jax.pure_callback(host_fn, tuple(result_shape), *xs)
    # tuple-wrapped list: "one duplicable output slot holding len(out)
    # items" (a bare 1-element list would be mis-split by the scatter)
    return (list(out),)
