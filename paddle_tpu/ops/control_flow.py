"""Control-flow ops: while, conditional_block, recurrent (StaticRNN), tensor
arrays, is_empty, print.

Parity targets: paddle/fluid/operators/controlflow/while_op.cc,
conditional_block_op.cc, operators/recurrent_op.cc,
operators/array_operator.h (write_to_array / read_from_array),
operators/lod_array_length_op.cc, operators/is_empty_op.cc,
operators/print_op.cc.

TPU-native execution model (vs the reference's scope-per-iteration
interpreter): the whole block is traced once into XLA, so loops take one of
two lowerings:

1. **Trace-time unroll** — when the loop condition is a *concrete* value at
   trace time (counter vs constant bound, the dominant pattern in fluid
   models: beam-search decode with a max_len counter, scheduled loops), the
   sub-block is re-traced per iteration in Python.  Tensor arrays are plain
   Python lists in the trace environment, so they may grow freely — XLA sees
   straight-line code.
2. **lax.while_loop** — when the condition is data-dependent (a traced
   value), the loop lowers to `jax.lax.while_loop` with the loop-carried
   variables gathered automatically from the sub-block's reads/writes.
   Tensor arrays cannot grow inside this form (XLA static shapes) — use a
   concrete bound instead, or `recurrent` (lax.scan) for fixed-length
   recurrence.

`recurrent` is the StaticRNN engine: lax.scan over the time axis, with
explicit Captured inputs so jax.vjp differentiates through the scan (the
reference builds recurrent_grad by block rewriting; here the scan is
natively differentiable).
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.lowering import run_op


def _is_concrete(x):
    """True when x is a trace-time constant (not a jax Tracer)."""
    return not isinstance(x, jax.core.Tracer)


_MAX_UNROLL = 10000


# ---------------------------------------------------------------------------
# tensor arrays.  Two representations:
#  * a Python list in the trace env (trace-time-indexed writes; grows freely
#    under unrolled loops — the fast, exact path), and
#  * BoundedTensorArray — a dense [capacity, ...] buffer + traced length,
#    registered as a jax pytree so arrays can be LOOP-CARRIED through
#    data-dependent `lax.while_loop`s and written at traced indices
#    (the reference's while_op + lod_tensor_to_array dynamic path,
#    controlflow/while_op.cc; capacity = FLAGS_tensor_array_max_len).
# ---------------------------------------------------------------------------


class BoundedTensorArray:
    """XLA-compatible tensor array: [capacity, *elem] buffer + int32 length."""

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length

    @property
    def capacity(self):
        return self.buffer.shape[0]


jax.tree_util.register_pytree_node(
    BoundedTensorArray,
    lambda a: ((a.buffer, a.length), None),
    lambda aux, ch: BoundedTensorArray(*ch),
)


def _array_capacity():
    from ..flags import flag

    return int(flag("tensor_array_max_len") or 256)


def _list_to_bounded(arr, template=None, capacity=None):
    """Materialize a python-list tensor array as a BoundedTensorArray.
    `template` supplies element shape/dtype when the list is empty.

    NB: jax clamps/drops out-of-bounds scatter updates SILENTLY, so
    capacity violations are checked wherever the index is known at trace
    time; a data-dependent loop must be bounded below
    FLAGS_tensor_array_max_len (raise the flag for longer decodes)."""
    elems = [e for e in (arr or []) if e is not None]
    if template is None:
        if not elems:
            raise ValueError(
                "cannot infer tensor-array element shape from an empty "
                "array; write one element before the dynamic loop")
        template = elems[0]
    cap = capacity or _array_capacity()
    n = len(arr or [])
    if n > cap:
        raise ValueError(
            "tensor array holds %d elements, over the dynamic-loop "
            "capacity %d (FLAGS_tensor_array_max_len)" % (n, cap))
    buf = jnp.zeros((cap,) + tuple(template.shape), template.dtype)
    for k, e in enumerate(arr or []):
        if e is not None:
            buf = buf.at[k].set(e.astype(buf.dtype))
    return BoundedTensorArray(buf, jnp.asarray(n, jnp.int32))


@register_op(
    "write_to_array",
    inputs=("X", "I", "Array"),
    outputs=("Out",),
    optional_inputs=("Array",),
    grad_maker=None,
    stateful=True,
)
def write_to_array(ctx, x, i, array):
    if isinstance(array, BoundedTensorArray) or not _is_concrete(i):
        if not isinstance(array, BoundedTensorArray):
            array = _list_to_bounded(array, template=x)
        if _is_concrete(i):
            ci = int(np.asarray(i).reshape(()))
            if ci >= array.capacity:
                raise ValueError(
                    "write_to_array index %d exceeds the dynamic-loop "
                    "capacity %d (FLAGS_tensor_array_max_len)"
                    % (ci, array.capacity))
            idx = jnp.asarray(ci, jnp.int32)
        else:
            idx = i.astype(jnp.int32).reshape(())
        buf = jax.lax.dynamic_update_index_in_dim(
            array.buffer, x.astype(array.buffer.dtype), idx, 0)
        length = jnp.maximum(array.length, idx + 1)
        return (BoundedTensorArray(buf, length),)
    idx = int(np.asarray(i).reshape(()))
    arr = list(array) if array is not None else []
    while len(arr) <= idx:
        arr.append(None)
    arr[idx] = x
    return (arr,)  # tuple-wrapped: a bare list would read as multi-output


@register_op(
    "read_from_array",
    inputs=("X", "I"),
    outputs=("Out",),
    grad_maker=None,
)
def read_from_array(ctx, x, i):
    if isinstance(x, BoundedTensorArray):
        idx = i.astype(jnp.int32).reshape(())
        return jax.lax.dynamic_index_in_dim(x.buffer, idx, 0,
                                            keepdims=False)
    if isinstance(x, list):
        if _is_concrete(i):
            return x[int(np.asarray(i).reshape(()))]
        # traced index over a materialized array: stack + dynamic gather
        stacked = jnp.stack([v for v in x])
        return stacked[i.astype(jnp.int32).reshape(())]
    return x[i.astype(jnp.int32).reshape(())]


@register_op(
    "lod_array_length",
    inputs=("X",),
    outputs=("Out",),
    grad_maker=None,
)
def lod_array_length(ctx, x):
    if isinstance(x, BoundedTensorArray):
        return x.length.astype(jnp.int64)
    return jnp.asarray(len(x) if isinstance(x, list) else x.shape[0],
                       dtype=jnp.int64)


@register_op(
    "is_empty",
    inputs=("X",),
    outputs=("Out",),
    grad_maker=None,
)
def is_empty(ctx, x):
    if isinstance(x, BoundedTensorArray):
        return x.length == 0
    if isinstance(x, list):
        return jnp.asarray(len(x) == 0)
    return jnp.asarray(int(np.prod(x.shape)) == 0)


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------


def _sub_block_reads_writes(block):
    """(reads-before-write, writes) of a sub-block, by name."""
    written = set()
    reads = []
    for op in block.ops:
        for n in op.input_arg_names:
            if n and n not in written and n not in reads:
                reads.append(n)
        for n in op.output_arg_names:
            if n:
                written.add(n)
    return reads, written


@register_op(
    "while",
    inputs=("X", "Condition"),
    outputs=("Out", "StepScopes"),
    attrs={"sub_block": -1, "is_test": False},
    duplicable_inputs=("X",),
    duplicable_outputs=("Out",),
    optional_inputs=("X",),
    grad_maker=None,
    stateful=True,
)
def while_op(ctx, xs, cond, sub_block=-1, is_test=False, **_):
    env = ctx.env
    block = ctx.block.program.block(sub_block)
    cond_name = ctx.op.input("Condition")[0]

    # trace-time unroll while the condition chain stays concrete; the
    # moment it becomes data-dependent (e.g. the loop body derives the
    # keep-going flag from decoded data), fall through to lax.while_loop
    # for the remaining iterations
    it = 0
    while _is_concrete(env[cond_name]):
        if not bool(np.asarray(env[cond_name]).reshape(())):
            return None, None
        key = jax.random.fold_in(ctx.rng(), it) if ctx._rng_key is not None else None
        ctx.run_sub_block(sub_block, env, key)
        it += 1
        if it > _MAX_UNROLL:
            raise RuntimeError("while unrolled past %d iterations" % _MAX_UNROLL)

    # data-dependent: lax.while_loop over automatically discovered carries
    reads, writes = _sub_block_reads_writes(block)
    carried = [n for n in reads if n in writes and n in env]
    for n in sorted(writes):
        if n in env and n not in carried:
            carried.append(n)
    if cond_name not in carried:
        raise RuntimeError(
            "while sub-block never updates its condition %r" % cond_name
        )
    # python-list tensor arrays become BoundedTensorArrays (dense buffer +
    # length, a registered pytree) so they carry through lax.while_loop
    for n in carried:
        if isinstance(env[n], list):
            env[n] = _list_to_bounded(env[n])
    outer = {k: v for k, v in env.items() if k not in carried}

    def cond_fn(carry):
        return jnp.asarray(carry[carried.index(cond_name)]).reshape(()) != 0

    def body_fn(carry):
        local = dict(outer)
        local.update(zip(carried, carry))
        for i, op in enumerate(block.ops):
            run_op(op, local, None, mesh=ctx.mesh, axis_names=ctx.axis_names)
        return tuple(local[n] for n in carried)

    init = tuple(env[n] for n in carried)
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    env.update(zip(carried, final))
    return None, None


# ---------------------------------------------------------------------------
# conditional_block
# ---------------------------------------------------------------------------


@register_op(
    "conditional_block",
    inputs=("Cond", "Input"),
    outputs=("Out", "Scope"),
    attrs={"sub_block": -1, "is_scalar_condition": True},
    duplicable_inputs=("Cond", "Input"),
    duplicable_outputs=("Out",),
    optional_inputs=("Input",),
    grad_maker=None,
    stateful=True,
)
def conditional_block(ctx, conds, inputs, sub_block=-1, is_scalar_condition=True, **_):
    env = ctx.env
    block = ctx.block.program.block(sub_block)
    cond = conds[0]
    if is_scalar_condition:
        pred = cond.reshape(())
    else:
        pred = jnp.all(cond)

    if _is_concrete(pred):
        if bool(np.asarray(pred)):
            ctx.run_sub_block(sub_block, env,
                              ctx.rng() if ctx._rng_key is not None else None)
        return None, None

    # traced predicate: lax.cond over the sub-block's written vars.  Vars the
    # branch would create fresh get zero-initialized defaults from an
    # abstract trace so both branches return the same structure.
    _, writes = _sub_block_reads_writes(block)
    writes = sorted(writes)
    outer = dict(env)

    def run_branch(_):
        local = dict(outer)
        for op in block.ops:
            run_op(op, local, None, mesh=ctx.mesh, axis_names=ctx.axis_names)
        return tuple(local[n] for n in writes)

    shapes = jax.eval_shape(run_branch, 0)
    defaults = tuple(
        env[n] if n in env else jnp.zeros(s.shape, s.dtype)
        for n, s in zip(writes, shapes)
    )

    def false_branch(_):
        return defaults

    out = jax.lax.cond(pred != 0, run_branch, false_branch, 0)
    env.update(zip(writes, out))
    return None, None


# ---------------------------------------------------------------------------
# recurrent (StaticRNN): lax.scan over the leading (time) axis
# ---------------------------------------------------------------------------


@register_op(
    "recurrent",
    inputs=("StepInputs", "Initials", "Captured"),
    outputs=("StepOutputs", "FinalStates"),
    attrs={
        "sub_block": -1,
        "step_input_names": [],   # inner per-step names, parallel to StepInputs
        "pre_state_names": [],    # inner names holding state(t-1)
        "state_names": [],        # inner names the block writes as state(t)
        "step_output_names": [],  # inner names stacked along time into StepOutputs
        "captured_names": [],     # inner==outer names of captured (weight) vars
        "reverse": False,
    },
    duplicable_inputs=("StepInputs", "Initials", "Captured"),
    duplicable_outputs=("StepOutputs", "FinalStates"),
    optional_inputs=("StepInputs", "Captured"),
    grad_maker="auto",
    stateful=False,
)
def recurrent(ctx, step_inputs, initials, captured, sub_block=-1,
              step_input_names=(), pre_state_names=(), state_names=(),
              step_output_names=(), captured_names=(), reverse=False, **_):
    block = ctx.block.program.block(sub_block)
    step_inputs = [x for x in (step_inputs or [])]
    captured = [x for x in (captured or [])]
    mesh, axis_names = ctx.mesh, ctx.axis_names

    base_key = ctx.rng() if ctx._rng_key is not None else None
    T = step_inputs[0].shape[0] if step_inputs else None
    if T is None:
        raise ValueError("recurrent requires at least one step input")

    def body(carry, xs):
        step_vals, key = xs
        env = dict(zip(captured_names, captured))
        env.update(zip(pre_state_names, carry))
        env.update(zip(step_input_names, step_vals))
        for i, op in enumerate(block.ops):
            k = jax.random.fold_in(key, i) if key is not None else None
            run_op(op, env, k, mesh=mesh, axis_names=axis_names)
        new_carry = tuple(env[n] for n in state_names)
        outs = tuple(env[n] for n in step_output_names)
        return new_carry, outs

    xs_stacked = tuple(step_inputs)
    if base_key is not None:
        keys = jax.random.split(base_key, T)
    else:
        # scan still needs a leaf of length T for the key slot
        keys = None
    init = tuple(initials)
    final, ys = jax.lax.scan(
        lambda c, x: body(c, x), init, (xs_stacked, keys), reverse=bool(reverse)
    )
    return list(ys), list(final)


def _recurrent_infer(op, block):
    prog = block.program
    sub = prog.block(op.attr("sub_block"))
    step_out_names = op.attr("step_output_names") or []
    sin = op.input("StepInputs")
    T = None
    if sin:
        v = block._find_var_recursive(sin[0])
        if v is not None and v.shape:
            T = v.shape[0]
    for outer_name, inner_name in zip(op.output("StepOutputs"), step_out_names):
        iv = sub._find_var_recursive(inner_name)
        ov = block._find_var_recursive(outer_name)
        if iv is not None and ov is not None and iv.shape is not None:
            ov.shape = (T,) + tuple(iv.shape) if T is not None else None
            ov.dtype = iv.dtype
    for outer_name, inner_name in zip(op.output("FinalStates"),
                                      op.attr("state_names") or []):
        iv = sub._find_var_recursive(inner_name)
        ov = block._find_var_recursive(outer_name)
        if iv is not None and ov is not None:
            ov.shape = iv.shape
            ov.dtype = iv.dtype


recurrent.opdef.infer_shape = _recurrent_infer


# ---------------------------------------------------------------------------
# print (debug passthrough; reference operators/print_op.cc)
# ---------------------------------------------------------------------------


@register_op(
    "print",
    inputs=("In",),
    outputs=("Out",),
    attrs={"message": "", "first_n": -1, "summarize": 20,
           "print_tensor_name": True, "print_tensor_type": True,
           "print_tensor_shape": True, "print_tensor_lod": False,
           "print_phase": "BOTH"},
    grad_maker=None,
)
def print_op(ctx, x, message="", first_n=-1, summarize=20,
             print_tensor_name=True, print_tensor_shape=True, **_):
    # host-side callback: first_n gating and summarize truncation run in
    # Python on each executed step (print_op.cc semantics)
    import numpy as _np

    count = [0]
    name = ctx.op.output("Out")[0] if ctx is not None and ctx.op else ""

    def _emit(val):
        count[0] += 1
        if first_n >= 0 and count[0] > first_n:
            return
        arr = _np.asarray(val)
        flat = arr.reshape(-1)
        shown = _np.array2string(flat[:summarize] if summarize >= 0 else flat)
        parts = [message]
        if print_tensor_name and name:
            parts.append(name)
        if print_tensor_shape:
            parts.append(str(arr.shape))
        parts.append(shown)
        print(" ".join(p for p in parts if p))

    jax.debug.callback(_emit, x)
    return x


# ---------------------------------------------------------------------------
# static shape inference: control-flow ops cannot be abstractly traced at
# append_op time (they need the live trace env), so give them explicit rules
# ---------------------------------------------------------------------------


def _noop_infer(op, block):
    return None


def _copy_x_infer(op, block):
    xv = block._find_var_recursive(op.input("In")[0])
    ov = block._find_var_recursive(op.output("Out")[0])
    if xv is not None and ov is not None:
        ov.shape = xv.shape
        if ov.dtype is None:
            ov.dtype = xv.dtype


for _t in ("write_to_array", "read_from_array", "while", "conditional_block"):
    from ..core.registry import get_op_def as _g

    _g(_t).infer_shape = _noop_infer

for _t in ("lod_array_length", "is_empty"):
    _g(_t).infer_shape = _noop_infer
_g("print").infer_shape = _copy_x_infer
