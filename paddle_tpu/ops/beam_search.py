"""Beam-search decode ops.

Parity: paddle/fluid/operators/beam_search_op.cc and
beam_search_decode_op.cc.  The reference works on LoD-ragged candidate
lists (variable beams per source); XLA needs static shapes, so the TPU
design keeps a dense fixed [batch, beam] layout and represents pruned /
finished beams with masked (-inf) scores — the LoD→mask translation from
SURVEY §5.

Protocol (mirrors the reference's decode loop in its transformer/NMT
examples): the caller seeds pre_scores with [0, -inf, ..., -inf] per batch
row so step 0 expands only beam 0 (all beams start identical), then each
step calls `beam_search` with the accumulated per-beam scores and the
next-token log-probs, writes selected ids/parents into tensor arrays, and
finally `beam_search_decode` backtracks parent pointers into full
sequences.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_NEG_INF = -1e9


@register_op(
    "beam_search",
    inputs=("pre_ids", "pre_scores", "ids", "scores"),
    outputs=("selected_ids", "selected_scores", "parent_idx"),
    attrs={"beam_size": 4, "end_id": 1, "level": 0, "is_accumulated": True},
    optional_inputs=("ids",),
    grad_maker=None,
)
def beam_search(ctx, pre_ids, pre_scores, ids, scores, beam_size=4, end_id=1,
                level=0, is_accumulated=True, **_):
    """One expansion step.

    pre_ids [B, K] int: last token per beam; pre_scores [B, K] float:
    accumulated log-prob per beam; scores [B, K, V] float: next-token
    log-probs (already accumulated with pre_scores when is_accumulated).
    Returns selected_ids [B, K], selected_scores [B, K], parent_idx [B, K].
    """
    B, K, V = scores.shape
    if not is_accumulated:
        scores = jnp.log(jnp.maximum(scores, 1e-20)) + pre_scores[..., None]
    finished = pre_ids.astype(jnp.int32) == end_id
    # finished beams emit only end_id, carrying their score unchanged
    only_end = jnp.full((B, K, V), _NEG_INF, scores.dtype)
    only_end = only_end.at[..., end_id].set(pre_scores)
    cand = jnp.where(finished[..., None], only_end, scores)
    flat = cand.reshape(B, K * V)
    sel_scores, flat_idx = jax.lax.top_k(flat, beam_size)
    parent = (flat_idx // V).astype(pre_ids.dtype)
    token = (flat_idx % V).astype(pre_ids.dtype)
    return token, sel_scores, parent


def _beam_search_infer(op, block):
    sv = block._find_var_recursive(op.input("scores")[0])
    K = int(op.attrs.get("beam_size", 4))
    if sv is not None and sv.shape is not None:
        B = sv.shape[0]
        for slot, dt in (("selected_ids", "int64"), ("selected_scores", None),
                         ("parent_idx", "int64")):
            ov = block._find_var_recursive(op.output(slot)[0])
            if ov is not None:
                ov.shape = (B, K)
                if ov.dtype is None:
                    ov.dtype = dt or sv.dtype


beam_search.opdef.infer_shape = _beam_search_infer


@register_op(
    "beam_search_decode",
    inputs=("Ids", "ParentIdx", "Scores"),
    outputs=("SentenceIds", "SentenceScores"),
    attrs={"beam_size": 4, "end_id": 1},
    optional_inputs=("Scores",),
    grad_maker=None,
)
def beam_search_decode(ctx, ids, parents, scores, beam_size=4, end_id=1, **_):
    """Backtrack parent pointers into full sequences.

    Ids / ParentIdx are tensor arrays (one [B, K] entry per step); Scores is
    the final [B, K] accumulated log-probs.  Returns SentenceIds [B, K, T]
    (end_id-padded past each beam's stop) and SentenceScores [B, K].
    """
    T = len(ids)
    B, K = ids[0].shape
    rows = jnp.arange(B)[:, None]
    beam = jnp.arange(K)[None, :].astype(ids[0].dtype) * jnp.ones(
        (B, 1), ids[0].dtype)
    seq = []
    for t in range(T - 1, -1, -1):
        b = beam.astype(jnp.int32)
        seq.append(ids[t][rows, b])
        beam = parents[t][rows, b]
    seq.reverse()
    sent = jnp.stack(seq, axis=-1)  # [B, K, T]
    if scores is None:
        scores = jnp.zeros((B, K), jnp.float32)
    # pad everything after the first end_id with end_id
    hit = jnp.cumsum((sent == end_id).astype(jnp.int32), axis=-1)
    sent = jnp.where(hit > 1, jnp.asarray(end_id, sent.dtype), sent)
    return sent, scores


beam_search_decode.opdef.infer_shape = lambda op, block: None
