"""Dense math ops: mul/matmul, elementwise family, reductions, scale/sum.

Parity targets: mul_op.cc, matmul_op.cc, elementwise/*.cc, reduce_ops/*.cc,
scale_op.cc, sum_op.cc, mean_op.cc, clip_op.cc (paddle/fluid/operators/).
All map onto the MXU via jnp dot/matmul; grads come from the auto vjp maker.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import bcast_y


def _flatten2d(x, num_col_dims):
    lead = 1
    for d in x.shape[:num_col_dims]:
        lead *= d
    rest = 1
    for d in x.shape[num_col_dims:]:
        rest *= d
    return jnp.reshape(x, (lead, rest))


def _amp_dot(ctx, x, y, contract_fn):
    """Matmul helper honoring the program's AMP policy: bf16 operands AND
    a bf16 result (bf16-carry).  On TPU the MXU accumulates bf16 products
    in f32 in hardware; the output dtype stays bf16 (not
    preferred_element_type=f32) so operand and cotangent dtypes remain
    uniform and the dot/conv transpose rules are well-typed under vjp.
    (XLA:CPU may round-trip partials through bf16 — test-only backend.)
    TPU-native replacement for the reference's fp16 cast-rewrite."""
    if ctx is not None and ctx.amp_bf16() and x.dtype in (jnp.float32,
                                                          jnp.bfloat16):
        # bf16-carry: the output STAYS bf16 even for f32 inputs, so the
        # whole activation stream downstream of the first matmul rides
        # bf16 (the loss lowerings upcast to f32 themselves).  The old
        # cast-back-to-f32-for-f32-inputs rule made the entire BERT
        # encoder carry f32 activations — every LN / residual / dropout /
        # attention-transpose pass moved twice the bytes (measured 28 ms
        # of f32 layout copies alone in the bs256 step).
        return contract_fn(x.astype(jnp.bfloat16), y.astype(jnp.bfloat16))
    return contract_fn(x, y)


@register_op(
    "mul",
    inputs=("X", "Y"),
    outputs=("Out",),
    attrs={"x_num_col_dims": 1, "y_num_col_dims": 1,
           "scale_x": 1.0, "scale_y": [1.0], "scale_out": 1.0,
           "force_fp32_output": False},
)
def mul(ctx, x, y, x_num_col_dims=1, y_num_col_dims=1, **_):
    """out[i, j] = sum_k x2d[i,k] y2d[k,j], with fluid's flatten-to-2D rule
    (mul_op.cc:37); output keeps the unflattened leading/trailing dims."""
    x2d = _flatten2d(x, x_num_col_dims)
    y2d = _flatten2d(y, y_num_col_dims)
    out = _amp_dot(ctx, x2d, y2d, jnp.dot)
    out_shape = x.shape[:x_num_col_dims] + y.shape[y_num_col_dims:]
    return jnp.reshape(out, out_shape)


@register_op(
    "matmul",
    inputs=("X", "Y"),
    outputs=("Out",),
    attrs={"transpose_X": False, "transpose_Y": False, "alpha": 1.0,
           "head_number": 1},
)
def matmul(ctx, x, y, transpose_X=False, transpose_Y=False, alpha=1.0,
           head_number=1):
    if x.ndim == y.ndim and x.ndim >= 2 and x.shape[:-2] == y.shape[:-2]:
        # dimension-order canonicalization: express the transpose flags as
        # dot_general contracting dims instead of materializing
        # jnp.transpose copies.  XLA folds the dimension numbers into the
        # MXU pass directly, so q@k^T / weight^T consumers stop paying a
        # layout copy per step.  Output is [batch..., M, N] for every flag
        # combination — identical to transpose-then-matmul.
        n = x.ndim
        batch = tuple(range(n - 2))
        cx = n - 2 if transpose_X else n - 1
        cy = n - 1 if transpose_Y else n - 2
        out = _amp_dot(
            ctx, x, y,
            lambda a, b: jax.lax.dot_general(
                a, b, (((cx,), (cy,)), (batch, batch))))
    else:
        # 1-D / rank-broadcast operands: numpy matmul semantics
        def t(a, flag):
            if not flag:
                return a
            if a.ndim == 1:
                return a
            perm = list(range(a.ndim))
            perm[-1], perm[-2] = perm[-2], perm[-1]
            return jnp.transpose(a, perm)

        x_, y_ = t(x, transpose_X), t(y, transpose_Y)
        # fluid allows [K] vectors: matmul handles 1-D semantics like numpy
        out = _amp_dot(ctx, x_, y_, jnp.matmul)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, dtype=out.dtype)
    return out


@register_op(
    "matmul_v2",
    inputs=("X", "Y"),
    outputs=("Out",),
    attrs={"trans_x": False, "trans_y": False},
)
def matmul_v2(ctx, x, y, trans_x=False, trans_y=False):
    return matmul(ctx, x, y, transpose_X=trans_x, transpose_Y=trans_y)


def _register_elementwise(name, fn):
    @register_op(
        "elementwise_" + name,
        inputs=("X", "Y"),
        outputs=("Out",),
        attrs={"axis": -1},
    )
    def _low(ctx, x, y, axis=-1, _fn=fn):
        if (ctx is not None and ctx.amp_bf16()
                and jnp.bfloat16 in (x.dtype, y.dtype)
                and jnp.float32 in (x.dtype, y.dtype)):
            # bf16-carry: a mixed bf16/f32 pair (bf16 activation + f32
            # bias/param) computes in bf16 — jnp promotion would silently
            # lift the whole activation stream back to f32
            x = x.astype(jnp.bfloat16)
            y = y.astype(jnp.bfloat16)
        yb = bcast_y(x, y, axis)
        return _fn(x, yb)

    return _low


_register_elementwise("add", jnp.add)
_register_elementwise("sub", jnp.subtract)
_register_elementwise("mul", jnp.multiply)
_register_elementwise("div", jnp.divide)
_register_elementwise("max", jnp.maximum)
_register_elementwise("min", jnp.minimum)
_register_elementwise("pow", jnp.power)
_register_elementwise("mod", jnp.mod)
_register_elementwise("floordiv", jnp.floor_divide)


@register_op("scale", inputs=("X", "ScaleTensor"), outputs=("Out",),
             attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True},
             optional_inputs=("ScaleTensor",))
def scale(ctx, x, scale_tensor, scale=1.0, bias=0.0, bias_after_scale=True):
    s = scale_tensor.reshape(()) if scale_tensor is not None else jnp.asarray(
        scale, dtype=x.dtype)
    b = jnp.asarray(bias, dtype=x.dtype)
    if bias_after_scale:
        return x * s + b
    return (x + b) * s


@register_op("sum", inputs=("X",), outputs=("Out",),
             duplicable_inputs=("X",))
def sum_op(ctx, xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_op("mean", inputs=("X",), outputs=("Out",))
def mean(ctx, x):
    return jnp.mean(x).reshape((1,))


def _reduce_dims(x, dim, reduce_all):
    if reduce_all or dim is None or len(dim) == 0:
        return None
    return tuple(d if d >= 0 else d + x.ndim for d in dim)


def _register_reduce(name, fn):
    @register_op(
        "reduce_" + name,
        inputs=("X",),
        outputs=("Out",),
        attrs={"dim": [0], "keep_dim": False, "reduce_all": False},
    )
    def _low(ctx, x, dim=(0,), keep_dim=False, reduce_all=False, _fn=fn):
        axes = _reduce_dims(x, dim, reduce_all)
        out = _fn(x, axis=axes, keepdims=keep_dim)
        if out.ndim == 0:
            out = out.reshape((1,))
        return out

    return _low


_register_reduce("sum", jnp.sum)
_register_reduce("mean", jnp.mean)
_register_reduce("max", jnp.max)
_register_reduce("min", jnp.min)
_register_reduce("prod", jnp.prod)
_register_reduce("all", jnp.all)
_register_reduce("any", jnp.any)


@register_op("clip", inputs=("X", "Min", "Max"), outputs=("Out",),
             attrs={"min": 0.0, "max": 0.0},
             optional_inputs=("Min", "Max"))
def clip(ctx, x, min_t, max_t, min=0.0, max=0.0):
    lo = min_t.reshape(()) if min_t is not None else min
    hi = max_t.reshape(()) if max_t is not None else max
    return jnp.clip(x, lo, hi)


@register_op("clip_by_norm", inputs=("X",), outputs=("Out",),
             attrs={"max_norm": 1.0})
def clip_by_norm(ctx, x, max_norm=1.0):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return x * scale


@register_op("squared_l2_norm", inputs=("X",), outputs=("Out",))
def squared_l2_norm(ctx, x):
    return jnp.sum(jnp.square(x)).reshape((1,))


@register_op("increment", inputs=("X",), outputs=("Out",),
             attrs={"step": 1.0}, grad_maker=None)
def increment(ctx, x, step=1.0):
    return x + jnp.asarray(step, dtype=x.dtype)


@register_op("p_norm", inputs=("X",), outputs=("Out",),
             attrs={"porder": 2.0, "axis": -1, "epsilon": 1e-12,
                    "keepdim": False, "asvector": False})
def p_norm(ctx, x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False,
           asvector=False):
    if asvector:
        x = x.reshape(-1)
        axis = 0
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim)
        + epsilon,
        1.0 / porder,
    )


@register_op("dot", inputs=("X", "Y"), outputs=("Out",))
def dot(ctx, x, y):
    return jnp.sum(x * y, axis=-1, keepdims=True)


@register_op("cumsum", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "flatten": False, "exclusive": False,
                    "reverse": False})
def cumsum(ctx, x, axis=-1, flatten=False, exclusive=False, reverse=False):
    if flatten:
        x = x.reshape(-1)
        axis = 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out
