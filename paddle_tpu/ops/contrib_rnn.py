"""contrib basic_gru / basic_lstm RNN ops.

TPU-native lowering of the reference's contrib composite RNN API
(python/paddle/fluid/contrib/layers/rnn_impl.py:139 basic_gru, :358
basic_lstm, :22 BasicGRUUnit, :632 BasicLSTMUnit).  The reference builds
these with StaticRNN — a per-step unrolled graph; here ONE op lowers the
whole single-direction multi-layer recurrence to a `lax.scan` (static
shapes, compiler-friendly control flow, weights stay resident in the
loop), which is the idiomatic XLA shape for an RNN.  The layer-stacking,
per-step dropout-between-layers, and padded-step masking semantics are
the reference's exactly:

    u_t, r_t = actGate(W_g [x_t, h_{t-1}] + b_g).split(2)   (GRU; r first)
    m_t = actNode(W_c [x_t, r_t*h_{t-1}] + b_c)
    h_t = u_t * h_{t-1} + (1 - u_t) * m_t
    masked:  h_t = h_t * m + h_{t-1} * (1 - m)

    i,j,f,o = (W [x_t, h_{t-1}] + b).split(4)               (LSTM)
    c_t = c_{t-1} * sigmoid(f + forget_bias) + sigmoid(i) * tanh(j)
    h_t = tanh(c_t) * sigmoid(o)

Dropout applies to the layer-(i) output as it feeds layer i+1 AND to the
final layer's step output (the reference appends the post-dropout
step_input as the last step_output and returns it), but NOT to the
per-layer last_hidden states.  The two APIs use DIFFERENT dropout
implementations, matching the reference exactly: basic_gru
(rnn_impl.py:302) calls layers.dropout with the default
``downgrade_in_infer`` — train masks WITHOUT upscaling, inference scales
by (1-p) — while basic_lstm (rnn_impl.py:532) passes
``dropout_implementation='upscale_in_train'`` — train masks and divides
by (1-p), inference is the identity.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _act(name):
    try:
        return _ACTS[name]
    except KeyError:
        raise NotImplementedError(
            "basic_gru/basic_lstm activation %r (supported: %s)"
            % (name, sorted(_ACTS)))


def _uses_dropout(attrs):
    return (float(attrs.get("dropout_prob", 0.0) or 0.0) > 0.0
            and not attrs.get("is_test", False))


def _step_keys(ctx, attrs, t_steps):
    # typed key array: lax.scan unstacks it per step and fold_in(key, i)
    # derives the per-layer streams (wrap_key_data would reject the
    # scan-unstacked 0-d typed key)
    if _uses_dropout(attrs):
        return jax.random.split(ctx.rng(), t_steps)
    return jnp.zeros((t_steps, 2), jnp.uint32)


def _dropout(x, p, key, upscale):
    """upscale=True: upscale_in_train (mask + x/(1-p)) — the LSTM path.
    upscale=False: downgrade_in_infer's TRAIN side (mask only, no
    rescale) — the GRU path; its (1-p) inference scaling is applied by
    the callers on their is_test branch."""
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    kept = x / (1.0 - p) if upscale else x
    return jnp.where(keep, kept, 0.0).astype(x.dtype)


@register_op(
    "basic_gru_rnn",
    inputs=("Input", "InitHidden", "Mask", "GateWeight", "CandWeight",
            "GateBias", "CandBias"),
    outputs=("Out", "LastHidden"),
    attrs={"hidden_size": 0, "num_layers": 1, "dropout_prob": 0.0,
           "is_test": False, "gate_activation": "sigmoid",
           "activation": "tanh"},
    optional_inputs=("InitHidden", "Mask"),
    duplicable_inputs=("GateWeight", "CandWeight", "GateBias", "CandBias"),
    n_rng=1,
)
def basic_gru_rnn(ctx, x, h0, mask, gate_w, cand_w, gate_b, cand_b,
                  hidden_size=0, num_layers=1, dropout_prob=0.0,
                  is_test=False, gate_activation="sigmoid",
                  activation="tanh"):
    """Single-direction multi-layer GRU over time-major input [T, B, I].

    h0: [L, B, H] or None (zeros).  mask: [T, B] or None.  Per-layer
    weights: gate_w[i] [I_i+H, 2H], cand_w[i] [I_i+H, H].  Returns
    (out [T, B, H], last_hidden [L, B, H])."""
    g_act = _act(gate_activation)
    c_act = _act(activation)
    T, B = x.shape[0], x.shape[1]
    H, L = int(hidden_size), int(num_layers)
    p = 0.0 if is_test else float(dropout_prob)
    # downgrade_in_infer (reference basic_gru's layers.dropout default):
    # inference multiplies by (1-p) where training masked
    infer_scale = (1.0 - float(dropout_prob)
                   if is_test and float(dropout_prob) > 0.0 else None)
    if h0 is None:
        h0 = jnp.zeros((L, B, H), x.dtype)
    else:
        h0 = h0.reshape(L, B, H).astype(x.dtype)
    keys = _step_keys(ctx, {"dropout_prob": p, "is_test": is_test}, T)
    ms = mask if mask is not None else jnp.ones((T, B), x.dtype)

    def step(h_carry, xs):
        x_t, m_t, key_t = xs
        step_in = x_t
        new_h = []
        for i in range(L):
            h_prev = h_carry[i]
            cat = jnp.concatenate([step_in, h_prev], axis=1)
            gate = g_act(jnp.dot(cat, gate_w[i]) + gate_b[i])
            r, u = jnp.split(gate, 2, axis=1)
            cand_in = jnp.concatenate([step_in, r * h_prev], axis=1)
            m = c_act(jnp.dot(cand_in, cand_w[i]) + cand_b[i])
            nh = u * h_prev + (1.0 - u) * m
            if mask is not None:
                mt = m_t[:, None].astype(nh.dtype)
                nh = nh * mt + h_prev * (1.0 - mt)
            new_h.append(nh)
            step_in = nh
            if p > 0.0:
                step_in = _dropout(step_in, p,
                                   jax.random.fold_in(key_t, i),
                                   upscale=False)
            elif infer_scale is not None:
                step_in = (step_in * infer_scale).astype(step_in.dtype)
        return jnp.stack(new_h), step_in

    last_h, out = jax.lax.scan(step, h0, (x, ms, keys))
    return out, last_h


@register_op(
    "basic_lstm_rnn",
    inputs=("Input", "InitHidden", "InitCell", "Mask", "Weight", "Bias"),
    outputs=("Out", "LastHidden", "LastCell"),
    attrs={"hidden_size": 0, "num_layers": 1, "dropout_prob": 0.0,
           "is_test": False, "forget_bias": 1.0,
           "gate_activation": "sigmoid", "activation": "tanh"},
    optional_inputs=("InitHidden", "InitCell", "Mask"),
    duplicable_inputs=("Weight", "Bias"),
    n_rng=1,
)
def basic_lstm_rnn(ctx, x, h0, c0, mask, weight, bias, hidden_size=0,
                   num_layers=1, dropout_prob=0.0, is_test=False,
                   forget_bias=1.0, gate_activation="sigmoid",
                   activation="tanh"):
    """Single-direction multi-layer LSTM over time-major input [T, B, I].

    weight[i]: [I_i+H, 4H] (i, j, f, o gate order — reference
    BasicLSTMUnit.forward); bias[i]: [4H].  Returns (out, last_hidden
    [L,B,H], last_cell [L,B,H])."""
    g_act = _act(gate_activation)
    c_act = _act(activation)
    T, B = x.shape[0], x.shape[1]
    H, L = int(hidden_size), int(num_layers)
    p = 0.0 if is_test else float(dropout_prob)
    fb = jnp.asarray(forget_bias, jnp.float32)
    h0 = (jnp.zeros((L, B, H), x.dtype) if h0 is None
          else h0.reshape(L, B, H).astype(x.dtype))
    c0 = (jnp.zeros((L, B, H), x.dtype) if c0 is None
          else c0.reshape(L, B, H).astype(x.dtype))
    keys = _step_keys(ctx, {"dropout_prob": p, "is_test": is_test}, T)
    ms = mask if mask is not None else jnp.ones((T, B), x.dtype)

    def step(carry, xs):
        h_carry, c_carry = carry
        x_t, m_t, key_t = xs
        step_in = x_t
        new_h, new_c = [], []
        for i in range(L):
            h_prev, c_prev = h_carry[i], c_carry[i]
            cat = jnp.concatenate([step_in, h_prev], axis=1)
            gates = jnp.dot(cat, weight[i]) + bias[i]
            gi, gj, gf, go = jnp.split(gates, 4, axis=1)
            nc = (c_prev * g_act(gf + fb.astype(gf.dtype))
                  + g_act(gi) * c_act(gj))
            nh = c_act(nc) * g_act(go)
            if mask is not None:
                mt = m_t[:, None].astype(nh.dtype)
                nh = nh * mt + h_prev * (1.0 - mt)
                nc = nc * mt + c_prev * (1.0 - mt)
            new_h.append(nh)
            new_c.append(nc)
            step_in = nh
            if p > 0.0:
                # reference basic_lstm passes upscale_in_train explicitly
                step_in = _dropout(step_in, p,
                                   jax.random.fold_in(key_t, i),
                                   upscale=True)
        return (jnp.stack(new_h), jnp.stack(new_c)), step_in

    (last_h, last_c), out = jax.lax.scan(step, (h0, c0), (x, ms, keys))
    return out, last_h, last_c


def _rnn_rng_when(attrs):
    return _uses_dropout(attrs)


basic_gru_rnn.opdef.rng_when = _rnn_rng_when
basic_lstm_rnn.opdef.rng_when = _rnn_rng_when
