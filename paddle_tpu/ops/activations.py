"""Activation ops (~30 in the reference activation_op.cc) — all VPU-friendly
elementwise lowerings; XLA fuses them into adjacent matmuls/convs."""

import math

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _register_act(name, fn, attrs=None):
    @register_op(name, inputs=("X",), outputs=("Out",), attrs=attrs or {})
    def _low(ctx, x, _fn=fn, **kw):
        return _fn(x, **kw)

    return _low


_register_act("relu", jax.nn.relu)
_register_act("sigmoid", jax.nn.sigmoid)
_register_act("tanh", jnp.tanh)
_register_act("exp", jnp.exp)
_register_act("log", jnp.log)
_register_act("log2", jnp.log2)
_register_act("log10", jnp.log10)
_register_act("log1p", jnp.log1p)
_register_act("sqrt", jnp.sqrt)
_register_act("rsqrt", lambda x: jax.lax.rsqrt(x))
_register_act("abs", jnp.abs)
_register_act("square", jnp.square)
_register_act("reciprocal", lambda x: 1.0 / x)
_register_act("softplus", jax.nn.softplus)
_register_act("softsign", jax.nn.soft_sign)
_register_act("sin", jnp.sin)
_register_act("cos", jnp.cos)
_register_act("tan", jnp.tan)
_register_act("asin", jnp.arcsin)
_register_act("acos", jnp.arccos)
_register_act("atan", jnp.arctan)
_register_act("sinh", jnp.sinh)
_register_act("cosh", jnp.cosh)
_register_act("ceil", jnp.ceil)
_register_act("floor", jnp.floor)
_register_act("round", jnp.round)
_register_act("tanh_shrink", lambda x: x - jnp.tanh(x))
_register_act("silu", jax.nn.silu)
_register_act("swish", lambda x, beta=1.0: x * jax.nn.sigmoid(beta * x),
              attrs={"beta": 1.0})
_register_act("logsigmoid", jax.nn.log_sigmoid)
_register_act("sign", jnp.sign)
_register_act("erf", jax.scipy.special.erf)

_register_act(
    "leaky_relu",
    lambda x, alpha=0.02: jnp.where(x >= 0, x, alpha * x),
    attrs={"alpha": 0.02},
)
_register_act(
    "elu",
    lambda x, alpha=1.0: jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0)),
    attrs={"alpha": 1.0},
)
_register_act(
    "relu6",
    lambda x, threshold=6.0: jnp.clip(x, 0.0, threshold),
    attrs={"threshold": 6.0},
)
_register_act(
    "brelu",
    lambda x, t_min=0.0, t_max=24.0: jnp.clip(x, t_min, t_max),
    attrs={"t_min": 0.0, "t_max": 24.0},
)
_register_act(
    "hard_sigmoid",
    lambda x, slope=0.2, offset=0.5: jnp.clip(slope * x + offset, 0.0, 1.0),
    attrs={"slope": 0.2, "offset": 0.5},
)
_register_act(
    "hard_swish",
    lambda x, threshold=6.0, scale=6.0, offset=3.0: x
    * jnp.clip(x + offset, 0.0, threshold)
    / scale,
    attrs={"threshold": 6.0, "scale": 6.0, "offset": 3.0},
)
_register_act(
    "hard_shrink",
    lambda x, threshold=0.5: jnp.where(jnp.abs(x) > threshold, x, 0.0),
    attrs={"threshold": 0.5},
)
_register_act(
    "soft_shrink",
    lambda x, lambda_=0.5: jnp.sign(x) * jnp.maximum(jnp.abs(x) - lambda_, 0.0),
    attrs={"lambda": 0.5},
)
_register_act(
    "thresholded_relu",
    lambda x, threshold=1.0: jnp.where(x > threshold, x, 0.0),
    attrs={"threshold": 1.0},
)
_register_act(
    "stanh",
    lambda x, scale_a=0.67, scale_b=1.7159: scale_b * jnp.tanh(scale_a * x),
    attrs={"scale_a": 0.67, "scale_b": 1.7159},
)
import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gelu_bf16(x, approximate):
    return jax.nn.gelu(x.astype(jnp.float32),
                       approximate=approximate).astype(x.dtype)


def _gelu_bf16_fwd(x, approximate):
    return _gelu_bf16(x, approximate), x


def _gelu_bf16_bwd(approximate, x, dy):
    # The barrier stops XLA from CSE-ing this f32 upcast with the
    # forward's: without it the shared f32 pre-activation is MATERIALIZED
    # for the backward — an extra f32 tensor write+read per gelu (402 MB
    # per BERT-base ffn layer; profiled as
    # (bf16[32768,3072], f32[32768,3072]) dual-output fusions) — instead
    # of a free in-register recompute from the saved bf16 activation.
    xf = jax.lax.optimization_barrier(x).astype(jnp.float32)
    _, vjp = jax.vjp(
        lambda u: jax.nn.gelu(u, approximate=approximate), xf)
    (df,) = vjp(dy.astype(jnp.float32))
    return (df.astype(x.dtype),)


_gelu_bf16.defvjp(_gelu_bf16_fwd, _gelu_bf16_bwd)

_register_act(
    "gelu",
    # f32 internal erf/tanh for the bf16 carry dtype (cheap VPU work; the
    # converts fuse into the surrounding elementwise fusion).  bf16 takes
    # the custom vjp above so the backward re-casts instead of saving f32.
    lambda x, approximate=False: (
        _gelu_bf16(x, approximate) if x.dtype == jnp.bfloat16
        else jax.nn.gelu(x, approximate=approximate).astype(x.dtype)),
    attrs={"approximate": False},
)
_register_act(
    "pow",
    lambda x, factor=1.0: jnp.power(x, factor),
    attrs={"factor": 1.0},
)


@register_op("softmax", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "use_cudnn": False, "use_mkldnn": False})
def softmax(ctx, x, axis=-1, **_):
    if x.dtype == jnp.bfloat16:
        # f32 internal exp/sum (flash_attention and the loss head do the
        # same); output restores the bf16 carry dtype
        return jax.nn.softmax(x.astype(jnp.float32), axis=axis).astype(
            x.dtype)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1})
def log_softmax(ctx, x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("prelu", inputs=("X", "Alpha"), outputs=("Out",),
             attrs={"mode": "all"})
def prelu(ctx, x, alpha, mode="all"):
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    return jnp.where(x > 0, x, a * x)


@register_op("maxout", inputs=("X",), outputs=("Out",),
             attrs={"groups": 1, "axis": 1})
def maxout(ctx, x, groups=1, axis=1):
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)
