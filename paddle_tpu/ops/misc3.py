"""Third op batch: CTC alignment, chunk evaluation, hashing, image patch
extraction, dense sequence slice, trilinear resize, per-pair box encode.

Parity (paddle/fluid/operators/): ctc_align_op.cc, chunk_eval_op.cc,
hash_op.cc, im2sequence_op.cc, sequence_ops/sequence_slice_op.cc,
interpolate_op.cc (trilinear), detection/box_coder_op.cc (paired form),
gaussian_random_op.cc (batch-size-like form).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("ctc_align", inputs=("Input",), outputs=("Output",),
             attrs={"blank": 0, "merge_repeated": True}, grad_maker=None)
def ctc_align(ctx, x, blank=0, merge_repeated=True):
    """Greedy CTC decode (ctc_align_op.cc): [B, T, C] logits (or [B, T]
    argmax ids) -> [B, T] token ids padded with -1."""
    ids = jnp.argmax(x, axis=-1) if x.ndim == 3 else x.astype(jnp.int32)
    B, T = ids.shape
    prev = jnp.pad(ids, ((0, 0), (1, 0)), constant_values=-1)[:, :-1]
    keep = (ids != blank)
    if merge_repeated:
        keep = keep & (ids != prev)
    # stable left-compaction: position = cumsum(keep) - 1
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    # scatter kept ids left-compacted; unkept writes land in a scratch slot
    scratch = jnp.full((B, T + 1), -1, jnp.int64)
    scat_pos = jnp.where(keep, pos, T)
    scratch = scratch.at[b_idx, scat_pos].set(
        jnp.where(keep, ids.astype(jnp.int64), -1))
    return scratch[:, :T]


@register_op("chunk_eval", inputs=("Inference", "Label"),
             outputs=("Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"),
             attrs={"chunk_scheme": "IOB", "num_chunk_types": 1,
                    "excluded_chunk_types": []},
             grad_maker=None)
def chunk_eval(ctx, inference, label, chunk_scheme="IOB", num_chunk_types=1,
               excluded_chunk_types=()):
    """Chunk P/R/F1 (chunk_eval_op.cc) for the IOB scheme on dense [B, T]
    tag ids padded with -1: tag = B-type (2*t), I-type (2*t+1), outside =
    2*num_chunk_types."""
    if chunk_scheme != "IOB":
        raise NotImplementedError("chunk_eval: only the IOB scheme is "
                                  "implemented on this backend")
    inf = inference.reshape(inference.shape[0], -1).astype(jnp.int32)
    lab = label.reshape(label.shape[0], -1).astype(jnp.int32)
    valid = lab >= 0

    def chunk_starts(tags):
        # B-tag always starts; I-tag starts a chunk if it follows a
        # different chunk type or outside (IOB2-ish robust reading)
        is_b = (tags % 2 == 0) & (tags < 2 * num_chunk_types)
        is_i = (tags % 2 == 1) & (tags < 2 * num_chunk_types)
        ctype = tags // 2
        prev = jnp.pad(tags, ((0, 0), (1, 0)), constant_values=-2)[:, :-1]
        prev_in = (prev >= 0) & (prev < 2 * num_chunk_types)
        prev_type = jnp.where(prev_in, prev // 2, -1)
        start = is_b | (is_i & (prev_type != ctype))
        inside = is_b | is_i
        return start, inside, ctype

    si, ii_, ti = chunk_starts(inf)
    sl, il, tl = chunk_starts(lab)
    si, sl = si & valid, sl & valid
    ii_, il = ii_ & valid, il & valid
    if excluded_chunk_types:
        excl = jnp.zeros_like(ti, dtype=bool)
        for et in excluded_chunk_types:
            excl = excl | (ti == int(et)) | (tl == int(et))
        si, sl = si & ~(excl & ii_), sl & ~(excl & il)
        ii_, il = ii_ & ~excl, il & ~excl
    n_inf = jnp.sum(si)
    n_lab = jnp.sum(sl)
    B, T = ii_.shape
    # positional structural agreement inside the label chunk
    same = (ti == tl) & (si == sl) & (ii_ == il) & ii_ & il
    span_bad = (il & ~same)
    # exact-span requirement: the inference chunk must END where the label
    # chunk ends — a continuation (inside, not start) right after a label
    # chunk end invalidates it
    inf_cont_next = jnp.pad(ii_ & ~si, ((0, 0), (0, 1)))[:, 1:]
    lab_cont_next = jnp.pad(il & ~sl, ((0, 0), (0, 1)))[:, 1:]
    label_end = il & ~lab_cont_next
    span_bad = span_bad | (label_end & inf_cont_next)
    # propagate badness to the chunk's start via reverse cumulative or:
    def row_propagate(sl_row, bad_row):
        def step(carry, t):
            # iterate right-to-left: carry = badness of current open chunk
            bad = carry | bad_row[t]
            out = bad
            carry2 = jnp.where(sl_row[t], False, bad)
            return carry2, (out, t)

        _, (outs, _) = lax.scan(step, False, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    bad_at = jax.vmap(row_propagate)(sl, span_bad)
    n_correct = jnp.sum(sl & ~bad_at)
    prec = n_correct / jnp.maximum(n_inf, 1)
    rec = n_correct / jnp.maximum(n_lab, 1)
    f1 = jnp.where(n_correct > 0, 2 * prec * rec / (prec + rec), 0.0)
    i64 = lambda v: v.astype(jnp.int64)
    return (prec.astype(jnp.float32), rec.astype(jnp.float32),
            f1.astype(jnp.float32), i64(n_inf), i64(n_lab), i64(n_correct))


@register_op("hash", inputs=("X",), outputs=("Out",),
             attrs={"mod_by": 1, "num_hash": 1}, grad_maker=None)
def hash_op(ctx, x, mod_by=1, num_hash=1):
    """Multi-hash of int id rows into [N, num_hash] buckets (hash_op.cc,
    xxHash replaced by splitmix64-style mixing)."""
    ids = x.astype(jnp.uint32).reshape(x.shape[0], -1)

    def mix(v, salt):
        v = (v ^ (v >> 16)) * jnp.uint32((0x85EBCA6B + salt) & 0xFFFFFFFF)
        v = (v ^ (v >> 13)) * jnp.uint32(0xC2B2AE35)
        return v ^ (v >> 16)

    outs = []
    for h in range(num_hash):
        mixed = mix(ids, (2654435761 * (h + 1)) & 0xFFFFFFFF)
        combined = jnp.sum(mixed, axis=1) % jnp.uint32(mod_by)
        outs.append(combined)
    return jnp.stack(outs, axis=1).astype(jnp.int64)


@register_op("im2sequence", inputs=("X",), outputs=("Out",),
             attrs={"kernels": [1, 1], "strides": [1, 1],
                    "paddings": [0, 0]})
def im2sequence(ctx, x, kernels=(1, 1), strides=(1, 1), paddings=(0, 0)):
    """Image -> patch sequence (im2sequence_op.cc): [N, C, H, W] ->
    [N, OH*OW, C*kh*kw] (dense; the reference flattens batch into LoD)."""
    kh, kw = kernels
    p = list(paddings)
    if len(p) == 2:
        pads = [(p[0], p[0]), (p[1], p[1])]
    else:  # [up, left, down, right] (im2sequence_op.cc)
        pads = [(p[0], p[2]), (p[1], p[3])]
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides), pads,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    N, CKK, OH, OW = patches.shape
    return patches.reshape(N, CKK, OH * OW).transpose(0, 2, 1)


@register_op("sequence_slice_dense", inputs=("X", "Offset", "Length"),
             outputs=("Out",), no_grad_inputs=("Offset", "Length"))
def sequence_slice_dense(ctx, x, offset, length):
    """Per-row slice of padded sequences (sequence_slice_op.cc on dense
    [B, T, ...]): out[b] = x[b, off[b]:off[b]+len[b]] left-aligned, padded
    with zeros to max(length)."""
    B, T = x.shape[0], x.shape[1]
    off = offset.reshape(-1).astype(jnp.int32)
    ln = length.reshape(-1).astype(jnp.int32)
    idx = jnp.arange(T)[None, :] + off[:, None]
    idx = jnp.clip(idx, 0, T - 1)
    gathered = jnp.take_along_axis(
        x, idx.reshape(B, T, *([1] * (x.ndim - 2))), axis=1)
    mask = (jnp.arange(T)[None, :] < ln[:, None])
    mask = mask.reshape(B, T, *([1] * (x.ndim - 2)))
    return jnp.where(mask, gathered, 0)


@register_op("trilinear_interp", inputs=("X",), outputs=("Out",),
             attrs={"out_shape": [], "scale": 0.0, "align_corners": True})
def trilinear_interp(ctx, x, out_shape=(), scale=0.0, align_corners=True):
    N, C, D, H, W = x.shape
    if out_shape:
        od, oh, ow = [int(v) for v in out_shape]
    else:
        od, oh, ow = int(D * scale), int(H * scale), int(W * scale)
    if not align_corners:
        return jax.image.resize(x, (N, C, od, oh, ow), method="trilinear")
    # align_corners=True: sample at linspace(0, in-1, out) per axis
    from jax.scipy.ndimage import map_coordinates

    def axis_coords(n_in, n_out):
        if n_out == 1:
            return jnp.zeros((1,), jnp.float32)
        return jnp.linspace(0.0, n_in - 1.0, n_out)

    dz = axis_coords(D, od)
    dy = axis_coords(H, oh)
    dx = axis_coords(W, ow)
    gz, gy, gx = jnp.meshgrid(dz, dy, dx, indexing="ij")

    def one(img):  # [D, H, W]
        return map_coordinates(img, [gz, gy, gx], order=1)

    return jax.vmap(jax.vmap(one))(x)


@register_op("gaussian_random_like", inputs=("X",), outputs=("Out",),
             attrs={"mean": 0.0, "std": 1.0}, grad_maker=None, n_rng=1)
def gaussian_random_like(ctx, x, mean=0.0, std=1.0):
    return mean + std * jax.random.normal(ctx.rng(), x.shape, jnp.float32)


@register_op("box_encode_paired",
             inputs=("PriorBox", "TargetBox", "PriorBoxVar"),
             outputs=("OutputBox",), attrs={"variance": []},
             optional_inputs=("PriorBoxVar",),
             no_grad_inputs=("PriorBoxVar",), grad_maker=None)
def box_encode_paired(ctx, prior, target, prior_var=None, variance=()):
    """Row-paired center-size encode: prior[i] vs target[i] -> [P, 4]
    (the diagonal of box_coder's [T, P, 4] encode, used by ssd_loss)."""
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    tw = target[:, 2] - target[:, 0]
    th = target[:, 3] - target[:, 1]
    tcx = target[:, 0] + tw * 0.5
    tcy = target[:, 1] + th * 0.5
    if prior_var is not None:
        # per-prior variances [P, 4]
        v = [prior_var[:, i] for i in range(4)]
    elif variance:
        vv = jnp.asarray(variance, jnp.float32)
        v = [vv[i] for i in range(4)]
    else:
        v = [1.0] * 4
    return jnp.stack([
        (tcx - pcx) / jnp.maximum(pw, 1e-10) / v[0],
        (tcy - pcy) / jnp.maximum(ph, 1e-10) / v[1],
        jnp.log(jnp.maximum(tw / jnp.maximum(pw, 1e-10), 1e-10)) / v[2],
        jnp.log(jnp.maximum(th / jnp.maximum(ph, 1e-10), 1e-10)) / v[3],
    ], axis=1)


# -- save/load ops (operators/save_op.h, load_op) -----------------------------


@register_op("save", inputs=("X",), outputs=(), attrs={"file_path": "",
             "overwrite": True, "save_as_fp16": False}, grad_maker=None,
             stateful=True)
def save_op(ctx, x, file_path="", overwrite=True, save_as_fp16=False):
    """Write one variable to `file_path` as .npy (reference writes a custom
    binary stream; format differs, granularity matches)."""
    import os

    # np.save appends .npy when the suffix is missing — guard the real target
    target = file_path if file_path.endswith(".npy") else file_path + ".npy"
    d = os.path.dirname(file_path)
    if d:
        os.makedirs(d, exist_ok=True)

    def _write(arr):
        # checked inside the callback: the guard must fire per EXECUTION,
        # not once at trace time (save_op.h checks at each run)
        if not overwrite and os.path.exists(target):
            raise RuntimeError("%s exists and overwrite is False" % target)
        np.save(file_path, np.asarray(arr), allow_pickle=False)

    jax.debug.callback(_write, x.astype(jnp.float16) if save_as_fp16 else x)
    return ()


@register_op("load", inputs=(), outputs=("Out",), attrs={"file_path": "",
             "load_as_fp16": False}, grad_maker=None, stateful=True)
def load_op(ctx, file_path="", load_as_fp16=False):
    """Load a variable saved by the `save` op.  The file is read at trace
    (compile) time — static shapes require it; re-reading a changed file
    needs a fresh program (documented deviation from the reference's
    run-time read)."""
    p = file_path if file_path.endswith(".npy") else file_path + ".npy"
    import os

    arr = np.load(p if os.path.exists(p) else file_path, allow_pickle=False)
    if load_as_fp16:
        arr = arr.astype(np.float16)
    return jnp.asarray(arr)
