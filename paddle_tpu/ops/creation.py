"""Tensor creation / random / casting ops.

Parity targets: fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, assign_op.cc, cast_op.cc, fill_zeros_like_op.cc,
fill_constant_batch_size_like_op.cc (all under paddle/fluid/operators/).
Randomness is functional (threaded PRNG keys) instead of stateful curand.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import attr_dtype


@register_op(
    "fill_constant",
    inputs=("ShapeTensor", "ShapeTensorList", "ValueTensor"),
    outputs=("Out",),
    attrs={"shape": [], "value": 0.0, "dtype": 5, "force_cpu": False, "str_value": ""},
    optional_inputs=("ShapeTensor", "ShapeTensorList", "ValueTensor"),
    duplicable_inputs=("ShapeTensorList",),
    grad_maker=None,
)
def fill_constant(ctx, shape_tensor, shape_tensor_list, value_tensor, shape=(),
                  value=0.0, dtype=5, force_cpu=False, str_value=""):
    dt = attr_dtype(dtype)
    if str_value not in ("", None):
        value = float(str_value)
    if value_tensor is not None:
        value = value_tensor.reshape(())
    return jnp.full(tuple(int(s) for s in shape), value, dtype=dt)


@register_op(
    "fill_zeros_like",
    inputs=("X",),
    outputs=("Out",),
    grad_maker=None,
)
def fill_zeros_like(ctx, x):
    return jnp.zeros_like(x)


@register_op(
    "fill_any_like",
    inputs=("X",),
    outputs=("Out",),
    attrs={"value": 0.0, "dtype": -1},
    grad_maker=None,
)
def fill_any_like(ctx, x, value=0.0, dtype=-1):
    dt = x.dtype if dtype in (-1, None) else attr_dtype(dtype)
    return jnp.full_like(x, value, dtype=dt)


@register_op(
    "fill_constant_batch_size_like",
    inputs=("Input",),
    outputs=("Out",),
    attrs={"shape": [], "value": 0.0, "dtype": 5, "input_dim_idx": 0,
           "output_dim_idx": 0, "force_cpu": False},
    grad_maker=None,
)
def fill_constant_batch_size_like(ctx, input, shape=(), value=0.0, dtype=5,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    dt = attr_dtype(dtype)
    out_shape = list(int(s) for s in shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    return jnp.full(tuple(out_shape), value, dtype=dt)


@register_op(
    "uniform_random",
    inputs=("ShapeTensor", "ShapeTensorList"),
    outputs=("Out",),
    attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0, "dtype": 5,
           "diag_num": 0, "diag_step": 0, "diag_val": 1.0},
    optional_inputs=("ShapeTensor", "ShapeTensorList"),
    duplicable_inputs=("ShapeTensorList",),
    grad_maker=None,
    n_rng=1,
)
def uniform_random(ctx, shape_tensor, shape_tensor_list, shape=(), min=-1.0,
                   max=1.0, seed=0, dtype=5, diag_num=0, diag_step=0,
                   diag_val=1.0):
    dt = attr_dtype(dtype)
    key = jax.random.key(seed) if seed else ctx.rng()
    return jax.random.uniform(
        key, tuple(int(s) for s in shape), dtype=dt, minval=min, maxval=max
    )


@register_op(
    "gaussian_random",
    inputs=("ShapeTensor", "ShapeTensorList"),
    outputs=("Out",),
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
    optional_inputs=("ShapeTensor", "ShapeTensorList"),
    duplicable_inputs=("ShapeTensorList",),
    grad_maker=None,
    n_rng=1,
)
def gaussian_random(ctx, shape_tensor, shape_tensor_list, shape=(), mean=0.0,
                    std=1.0, seed=0, dtype=5):
    dt = attr_dtype(dtype)
    key = jax.random.key(seed) if seed else ctx.rng()
    return mean + std * jax.random.normal(key, tuple(int(s) for s in shape), dtype=dt)


@register_op(
    "truncated_gaussian_random",
    inputs=(),
    outputs=("Out",),
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
    grad_maker=None,
    n_rng=1,
)
def truncated_gaussian_random(ctx, shape=(), mean=0.0, std=1.0, seed=0, dtype=5):
    dt = attr_dtype(dtype)
    key = jax.random.key(seed) if seed else ctx.rng()
    x = jax.random.truncated_normal(key, -2.0, 2.0, tuple(int(s) for s in shape),
                                    dtype=dt)
    return mean + std * x


@register_op(
    "randint",
    inputs=(),
    outputs=("Out",),
    attrs={"shape": [], "low": 0, "high": 1, "seed": 0, "dtype": 3},
    grad_maker=None,
    n_rng=1,
)
def randint(ctx, shape=(), low=0, high=1, seed=0, dtype=3):
    dt = attr_dtype(dtype)
    key = jax.random.key(seed) if seed else ctx.rng()
    return jax.random.randint(key, tuple(int(s) for s in shape), low, high, dtype=dt)


@register_op("assign", inputs=("X",), outputs=("Out",))
def assign(ctx, x):
    return x


@register_op(
    "assign_value",
    inputs=(),
    outputs=("Out",),
    attrs={"shape": [], "dtype": 5, "fp32_values": [], "int32_values": [],
           "int64_values": [], "bool_values": []},
    grad_maker=None,
)
def assign_value(ctx, shape=(), dtype=5, fp32_values=(), int32_values=(),
                 int64_values=(), bool_values=()):
    dt = attr_dtype(dtype)
    vals = fp32_values or int32_values or int64_values or bool_values
    return jnp.asarray(np.array(vals), dtype=dt).reshape(tuple(int(s) for s in shape))


@register_op("cast", inputs=("X",), outputs=("Out",),
             attrs={"in_dtype": 5, "out_dtype": 5},
             grad_maker="auto")
def cast(ctx, x, in_dtype=5, out_dtype=5):
    return x.astype(attr_dtype(out_dtype))


@register_op("shape", inputs=("Input",), outputs=("Out",), grad_maker=None)
def shape_op(ctx, input):
    return jnp.asarray(np.array(input.shape, dtype=np.int32))


@register_op(
    "range",
    inputs=("Start", "End", "Step"),
    outputs=("Out",),
    optional_inputs=("Start", "End", "Step"),
    grad_maker=None,
)
def range_op(ctx, start, end, step):
    # static-shape requirement: bounds must be concrete on TPU
    s = float(np.asarray(start)) if start is not None else 0.0
    e = float(np.asarray(end))
    st = float(np.asarray(step)) if step is not None else 1.0
    return jnp.arange(s, e, st)


@register_op(
    "eye",
    inputs=(),
    outputs=("Out",),
    attrs={"num_rows": 0, "num_columns": -1, "dtype": 5},
    grad_maker=None,
)
def eye(ctx, num_rows=0, num_columns=-1, dtype=5):
    n = num_columns if num_columns > 0 else num_rows
    return jnp.eye(num_rows, n, dtype=attr_dtype(dtype))


@register_op(
    "linspace",
    inputs=("Start", "Stop", "Num"),
    outputs=("Out",),
    attrs={"dtype": 5},
    grad_maker=None,
)
def linspace(ctx, start, stop, num, dtype=5):
    n = int(np.asarray(num))
    return jnp.linspace(start.reshape(()), stop.reshape(()), n,
                        dtype=attr_dtype(dtype))


# feed/fetch are structural ops (executor handles data movement directly);
# registered so saved inference programs load & validate
# (reference: operators/controlflow/feed_op.cc, fetch_op.cc).
@register_op("feed", inputs=("X",), outputs=("Out",), attrs={"col": 0},
             grad_maker=None, optional_inputs=("X",))
def feed(ctx, x, col=0):
    return x


@register_op("fetch", inputs=("X",), outputs=("Out",), attrs={"col": 0},
             grad_maker=None, optional_inputs=("X",))
def fetch(ctx, x, col=0):
    return x
