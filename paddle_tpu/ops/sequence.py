"""Sequence ops over padded dense batches + explicit lengths.

Parity targets: paddle/fluid/operators/sequence_ops/ (sequence_pool_op.cc,
sequence_softmax_op.cc, sequence_expand_op.cc, sequence_pad_op.cc,
sequence_unpad_op.cc, sequence_conv_op.cc, sequence_reverse_op.h,
sequence_concat_op.cc, sequence_mask_op.cc…).

LoD design note (SURVEY.md §5 "Long-context"): the reference represents
variable-length batches as LoDTensor — a flat [total_tokens, D] buffer plus
ragged offsets — and its sequence kernels iterate offsets on the host.  That
layout cannot be compiled by XLA (dynamic shapes), and on TPU ragged
iteration wastes the MXU.  This framework instead uses the TPU-native
layout: **padded dense [batch, max_len, ...] tensors + a per-row Length
vector**, with masking inside the lowering.  The `sequence_*` op names and
semantics (pool/softmax/expand/reverse/conv per-sequence, respecting
lengths) are preserved; `Length` rides as an explicit optional input instead
of hidden LoD metadata.  XLA fuses every mask with its consumer, so the
masked forms cost ~0 extra HBM traffic.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .common import attr_dtype


def _time_mask(x_btd, length, dtype=None):
    """[B, T] validity mask from per-row lengths (None -> all valid)."""
    B, T = x_btd.shape[0], x_btd.shape[1]
    if length is None:
        m = jnp.ones((B, T), dtype=dtype or x_btd.dtype)
    else:
        t = jnp.arange(T)[None, :]
        m = (t < length.reshape(-1, 1)).astype(dtype or x_btd.dtype)
    return m


def _expand_mask(m, x):
    """Broadcast a [B, T] mask across x's trailing dims."""
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


@register_op(
    "sequence_mask",
    inputs=("X", "MaxLenTensor"),
    outputs=("Y",),
    attrs={"maxlen": -1, "out_dtype": 5},
    optional_inputs=("MaxLenTensor",),
    grad_maker=None,
)
def sequence_mask(ctx, x, maxlen_tensor, maxlen=-1, out_dtype=5):
    if maxlen_tensor is not None:
        maxlen = int(np.asarray(maxlen_tensor).reshape(()))
    if maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen on TPU (XLA static shapes); "
            "pass maxlen explicitly"
        )
    dt = attr_dtype(out_dtype)
    t = jnp.arange(maxlen)
    m = t.reshape((1,) * x.ndim + (maxlen,)) < x[..., None]
    return m.astype(dt)


@register_op(
    "sequence_pool",
    inputs=("X", "Length"),
    outputs=("Out", "MaxIndex"),
    attrs={"pooltype": "AVERAGE", "pad_value": 0.0},
    # MaxIndex is an OUTPUT (reference sequence_pool_op.cc emits it for the
    # MAX pool's backward); it was mistakenly listed as an optional input
    # here until OpDef grew def-level slot validation
    optional_inputs=("Length",),
)
def sequence_pool(ctx, x, length, pooltype="AVERAGE", pad_value=0.0):
    pooltype = pooltype.upper()
    m = _expand_mask(_time_mask(x, length), x)
    T = x.shape[1]
    if length is None:
        n = jnp.full((x.shape[0],) + (1,) * (x.ndim - 2), float(T), x.dtype)
    else:
        n = jnp.maximum(length.astype(x.dtype), 1).reshape(
            (-1,) + (1,) * (x.ndim - 2))
    if pooltype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif pooltype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / n
    elif pooltype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(n)
    elif pooltype == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif pooltype == "LAST":
        if length is None:
            out = x[:, -1]
        else:
            idx = jnp.maximum(length.astype(jnp.int32) - 1, 0).reshape(-1)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
            ).squeeze(1)
    elif pooltype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % pooltype)
    if length is not None and pooltype in ("MAX", "LAST", "FIRST"):
        valid = (length > 0).reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.where(valid, out, jnp.asarray(pad_value, out.dtype))
    return out, None


@register_op(
    "sequence_softmax",
    inputs=("X", "Length"),
    outputs=("Out",),
    optional_inputs=("Length",),
)
def sequence_softmax(ctx, x, length):
    # x: [B, T] (or [B, T, 1]); softmax over the valid T per row
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    v = x.reshape(x.shape[:2]) if squeeze else x
    m = _time_mask(v, length, dtype=jnp.bool_)
    neg = jnp.asarray(jnp.finfo(v.dtype).min, v.dtype)
    logits = jnp.where(m, v, neg)
    out = jax.nn.softmax(logits, axis=1)
    out = jnp.where(m, out, jnp.zeros_like(out))
    return out.reshape(x.shape) if squeeze else out


@register_op(
    "sequence_expand",
    inputs=("X", "Y", "RefLength"),
    outputs=("Out",),
    attrs={"ref_level": -1},
    optional_inputs=("RefLength",),
    no_grad_inputs=("Y", "RefLength"),
)
def sequence_expand(ctx, x, y, ref_length=None, ref_level=-1):
    """Padded semantics of sequence_expand_op.cc: broadcast x [B, ...]
    along y's padded expansion axis -> [B, R, ...].  Multi-level LoD
    (ref_level selecting which nesting level's counts drive the expansion,
    lod_tensor.h:52): the caller passes y padded at that level — for a
    level-2 y [B, S, T, ...], ref_level=0 expands over S (pass y's
    [B, S, ...] view), ref_level=1 over T — and the optional RefLength [B]
    carries that level's true counts, masking rows past each sample's
    count (the ragged tail)."""
    R = y.shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], R) + tuple(x.shape[1:]))
    if ref_length is not None:
        mask = (jnp.arange(R)[None, :]
                < ref_length.reshape(-1, 1)).astype(out.dtype)
        out = out * mask.reshape(mask.shape + (1,) * (out.ndim - 2))
    return out


@register_op(
    "sequence_expand_as",
    inputs=("X", "Y"),
    outputs=("Out",),
    no_grad_inputs=("Y",),
)
def sequence_expand_as(ctx, x, y):
    T = y.shape[1]
    return jnp.broadcast_to(x[:, None], (x.shape[0], T) + tuple(x.shape[1:]))


@register_op(
    "sequence_reverse",
    inputs=("X", "Length"),
    outputs=("Y",),
    optional_inputs=("Length",),
)
def sequence_reverse(ctx, x, length):
    T = x.shape[1]
    if length is None:
        return jnp.flip(x, axis=1)
    t = jnp.arange(T)[None, :]
    L = length.reshape(-1, 1).astype(jnp.int32)
    idx = jnp.where(t < L, L - 1 - t, t)  # reverse valid prefix, keep pad
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1
    )


@register_op(
    "sequence_pad",
    inputs=("X", "PadValue", "Length"),
    outputs=("Out", "Length@OUT"),
    attrs={"padded_length": -1},
    optional_inputs=("Length",),
    no_grad_inputs=("PadValue", "Length"),
)
def sequence_pad(ctx, x, pad_value, length, padded_length=-1):
    # already-padded world: fill positions beyond each row's length with
    # pad_value (and optionally re-pad time to padded_length).  Lengths
    # default to the ORIGINAL time extent (before any re-pad) so Length out
    # reports true pre-pad row lengths.
    orig_T = x.shape[1]
    L = length if length is not None else jnp.full(
        (x.shape[0],), orig_T, jnp.int64)
    if padded_length > 0 and padded_length != orig_T:
        if padded_length > orig_T:
            pad = [(0, 0)] * x.ndim
            pad[1] = (0, padded_length - orig_T)
            x = jnp.pad(x, pad)
        else:
            x = x[:, :padded_length]
    m = _expand_mask(_time_mask(x, L), x)
    pv = pad_value.reshape(()) if pad_value is not None else jnp.asarray(0, x.dtype)
    out = x * m + (1 - m) * pv.astype(x.dtype)
    return out, L


@register_op(
    "sequence_unpad",
    inputs=("X", "Length"),
    outputs=("Out",),
    no_grad_inputs=("Length",),
)
def sequence_unpad(ctx, x, length):
    # padded world: zero out the padding (shape stays static)
    m = _expand_mask(_time_mask(x, length), x)
    return x * m


@register_op(
    "sequence_concat",
    inputs=("X",),
    outputs=("Out",),
    duplicable_inputs=("X",),
)
def sequence_concat(ctx, xs):
    return jnp.concatenate(list(xs), axis=1)


@register_op(
    "sequence_conv",
    inputs=("X", "Filter", "PaddingData", "Length"),
    outputs=("Out",),
    attrs={"contextLength": 3, "contextStart": -1, "contextStride": 1,
           "paddingTrainable": False},
    optional_inputs=("PaddingData", "Length"),
    no_grad_inputs=("PaddingData", "Length"),
)
def sequence_conv(ctx, x, filt, padding_data, length, contextLength=3,
                  contextStart=-1, contextStride=1, paddingTrainable=False):
    # x: [B, T, D]; filter: [contextLength*D, M] -> out [B, T, M]
    if contextStride != 1:
        raise NotImplementedError("sequence_conv contextStride must be 1")
    B, T, D = x.shape
    m = _expand_mask(_time_mask(x, length), x)
    xm = x * m
    cols = []
    for k in range(contextLength):
        off = contextStart + k
        shifted = jnp.roll(xm, -off, axis=1)
        t = jnp.arange(T)
        valid = ((t + off) >= 0) & ((t + off) < T)
        cols.append(shifted * valid[None, :, None].astype(x.dtype))
    im = jnp.concatenate(cols, axis=-1)  # [B, T, ctx*D]
    out = jnp.einsum("btc,cm->btm", im, filt)
    return out * _expand_mask(_time_mask(out, length), out)


@register_op(
    "sequence_enumerate",
    inputs=("X",),
    outputs=("Out",),
    attrs={"win_size": 2, "pad_value": 0},
    grad_maker=None,
)
def sequence_enumerate(ctx, x, win_size=2, pad_value=0):
    # x: [B, T] int ids -> [B, T, win_size] sliding windows padded w/ pad_value
    B, T = x.shape[0], x.shape[1]
    outs = []
    for k in range(win_size):
        shifted = jnp.roll(x, -k, axis=1)
        valid = (jnp.arange(T) + k) < T
        outs.append(jnp.where(valid[None, :], shifted,
                              jnp.asarray(pad_value, x.dtype)))
    return jnp.stack(outs, axis=-1)
