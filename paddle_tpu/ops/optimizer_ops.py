"""Optimizer update ops — fused XLA update computations.

Parity: paddle/fluid/operators/optimizers/ (sgd_op.cc, momentum_op.cc,
adam_op.cc, adagrad_op.cc, rmsprop_op.cc, lamb_op.cc, lars_momentum_op.cc,
adadelta_op.cc, adamax_op.cc, decayed_adagrad_op.cc, ftrl_op.cc,
proximal_gd_op.cc).  Each op is pure: reads Param/accumulators, returns the
updated values; the executor stores them back to the scope (donated buffers,
so updates are in-place at the XLA level).
"""

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lr(lr):
    return lr.reshape(())


@register_op("sgd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), grad_maker=None)
def sgd(ctx, param, grad, lr):
    return param - _lr(lr).astype(param.dtype) * grad.astype(param.dtype)


@register_op(
    "momentum",
    inputs=("Param", "Grad", "Velocity", "LearningRate"),
    outputs=("ParamOut", "VelocityOut"),
    attrs={"mu": 0.0, "use_nesterov": False, "regularization_method": "",
           "regularization_coeff": 0.0},
    grad_maker=None,
)
def momentum(ctx, param, grad, velocity, lr, mu=0.0, use_nesterov=False,
             regularization_method="", regularization_coeff=0.0):
    lr = _lr(lr).astype(param.dtype)
    g = grad.astype(param.dtype)
    if regularization_method == "l2_decay":
        g = g + regularization_coeff * param
    v = mu * velocity + g
    if use_nesterov:
        p = param - (g + mu * v) * lr
    else:
        p = param - lr * v
    return p, v


@register_op(
    "adam",
    inputs=("Param", "Grad", "Moment1", "Moment2", "LearningRate",
            "Beta1Pow", "Beta2Pow", "Beta1Tensor", "Beta2Tensor"),
    outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"),
    attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "lazy_mode": False,
           "min_row_size_to_use_multithread": 1000},
    optional_inputs=("Beta1Tensor", "Beta2Tensor"),
    grad_maker=None,
)
def adam(ctx, param, grad, m1, m2, lr, b1pow, b2pow, b1t, b2t, beta1=0.9,
         beta2=0.999, epsilon=1e-8, **_):
    dt = param.dtype
    beta1 = b1t.reshape(()).astype(dt) if b1t is not None else jnp.asarray(beta1, dt)
    beta2 = b2t.reshape(()).astype(dt) if b2t is not None else jnp.asarray(beta2, dt)
    lr = _lr(lr).astype(dt)
    g = grad.astype(dt)
    m1n = beta1 * m1 + (1.0 - beta1) * g
    m2n = beta2 * m2 + (1.0 - beta2) * g * g
    b1p = b1pow.reshape(()).astype(dt)
    b2p = b2pow.reshape(()).astype(dt)
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    p = param - lr_t * m1n / (jnp.sqrt(m2n) + epsilon)
    return p, m1n, m2n, (b1pow * beta1).astype(b1pow.dtype), (
        b2pow * beta2
    ).astype(b2pow.dtype)


@register_op(
    "adamax",
    inputs=("Param", "Grad", "Moment", "InfNorm", "LearningRate", "Beta1Pow"),
    outputs=("ParamOut", "MomentOut", "InfNormOut"),
    attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
    grad_maker=None,
)
def adamax(ctx, param, grad, moment, inf_norm, lr, b1pow, beta1=0.9,
           beta2=0.999, epsilon=1e-8):
    lr = _lr(lr)
    m = beta1 * moment + (1.0 - beta1) * grad
    inf = jnp.maximum(beta2 * inf_norm, jnp.abs(grad) + epsilon)
    lr_t = lr / (1.0 - b1pow.reshape(()))
    p = param - lr_t * m / inf
    return p, m, inf


@register_op(
    "adagrad",
    inputs=("Param", "Grad", "Moment", "LearningRate"),
    outputs=("ParamOut", "MomentOut"),
    attrs={"epsilon": 1e-6},
    grad_maker=None,
)
def adagrad(ctx, param, grad, moment, lr, epsilon=1e-6):
    m = moment + grad * grad
    p = param - _lr(lr) * grad / (jnp.sqrt(m) + epsilon)
    return p, m


@register_op(
    "decayed_adagrad",
    inputs=("Param", "Grad", "Moment", "LearningRate"),
    outputs=("ParamOut", "MomentOut"),
    attrs={"decay": 0.95, "epsilon": 1e-6},
    grad_maker=None,
)
def decayed_adagrad(ctx, param, grad, moment, lr, decay=0.95, epsilon=1e-6):
    m = decay * moment + (1.0 - decay) * grad * grad
    p = param - _lr(lr) * grad / (jnp.sqrt(m) + epsilon)
    return p, m


@register_op(
    "adadelta",
    inputs=("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"),
    outputs=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"),
    attrs={"rho": 0.95, "epsilon": 1e-6},
    grad_maker=None,
)
def adadelta(ctx, param, grad, avg_sq_grad, avg_sq_update, rho=0.95,
             epsilon=1e-6):
    g2 = rho * avg_sq_grad + (1.0 - rho) * grad * grad
    update = -jnp.sqrt((avg_sq_update + epsilon) / (g2 + epsilon)) * grad
    u2 = rho * avg_sq_update + (1.0 - rho) * update * update
    return param + update, g2, u2


@register_op(
    "rmsprop",
    inputs=("Param", "Grad", "MeanSquare", "MeanGrad", "Moment",
            "LearningRate"),
    outputs=("ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"),
    attrs={"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10, "centered": False},
    optional_inputs=("MeanGrad",),
    grad_maker=None,
)
def rmsprop(ctx, param, grad, mean_square, mean_grad, moment, lr, decay=0.9,
            momentum=0.0, epsilon=1e-10, centered=False):
    lr = _lr(lr)
    ms = decay * mean_square + (1.0 - decay) * grad * grad
    if centered:
        mg = decay * mean_grad + (1.0 - decay) * grad
        mom = momentum * moment + lr * grad / jnp.sqrt(ms - mg * mg + epsilon)
    else:
        mg = mean_grad
        mom = momentum * moment + lr * grad / jnp.sqrt(ms + epsilon)
    p = param - mom
    return p, mom, ms, mg


@register_op(
    "lars_momentum",
    inputs=("Param", "Grad", "Velocity", "LearningRate"),
    outputs=("ParamOut", "VelocityOut"),
    attrs={"mu": 0.0, "lars_coeff": 0.001, "lars_weight_decay": 0.0005,
           "epsilon": 0.0},
    grad_maker=None,
)
def lars_momentum(ctx, param, grad, velocity, lr, mu=0.0, lars_coeff=0.001,
                  lars_weight_decay=0.0005, epsilon=0.0):
    lr = _lr(lr)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(param)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(grad)))
    local_lr = lr * lars_coeff * p_norm / (
        g_norm + lars_weight_decay * p_norm + epsilon + 1e-20
    )
    v = mu * velocity + local_lr * (grad + lars_weight_decay * param)
    return param - v, v


@register_op(
    "lamb",
    inputs=("Param", "Grad", "Moment1", "Moment2", "LearningRate",
            "Beta1Pow", "Beta2Pow"),
    outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"),
    attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6, "weight_decay": 0.01},
    grad_maker=None,
)
def lamb(ctx, param, grad, m1, m2, lr, b1pow, b2pow, beta1=0.9, beta2=0.999,
         epsilon=1e-6, weight_decay=0.01):
    lr = _lr(lr)
    m1n = beta1 * m1 + (1.0 - beta1) * grad
    m2n = beta2 * m2 + (1.0 - beta2) * grad * grad
    b1p = b1pow.reshape(())
    b2p = b2pow.reshape(())
    m1h = m1n / (1.0 - b1p)
    m2h = m2n / (1.0 - b2p)
    r = m1h / (jnp.sqrt(m2h) + epsilon) + weight_decay * param
    w_norm = jnp.sqrt(jnp.sum(jnp.square(param)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    p = param - lr * ratio * r
    return p, m1n, m2n, b1pow * beta1, b2pow * beta2


@register_op(
    "ftrl",
    inputs=("Param", "SquaredAccumulator", "LinearAccumulator", "Grad",
            "LearningRate"),
    outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"),
    attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
    grad_maker=None,
)
def ftrl(ctx, param, sq_accum, lin_accum, grad, lr, l1=0.0, l2=0.0,
         lr_power=-0.5):
    lr = _lr(lr)
    new_accum = sq_accum + grad * grad
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_accum) - jnp.sqrt(sq_accum)) / lr
    else:
        sigma = (jnp.power(new_accum, -lr_power) - jnp.power(sq_accum, -lr_power)) / lr
    lin = lin_accum + grad - sigma * param
    if lr_power == -0.5:
        denom = jnp.sqrt(new_accum) / lr + 2 * l2
    else:
        denom = jnp.power(new_accum, -lr_power) / lr + 2 * l2
    pre = jnp.clip(lin, -l1, l1) - lin
    p = jnp.where(jnp.abs(lin) > l1, pre / denom, jnp.zeros_like(param))
    return p, new_accum, lin


@register_op(
    "dpsgd",
    inputs=("Param", "Grad", "LearningRate"),
    outputs=("ParamOut",),
    attrs={"clip": 10.0, "batch_size": 16.0, "sigma": 1.0, "seed": 0},
    grad_maker=None,
    n_rng=1,
)
def dpsgd(ctx, param, grad, lr, clip=10.0, batch_size=16.0, sigma=1.0, seed=0):
    import jax

    g_norm = jnp.sqrt(jnp.sum(jnp.square(grad)))
    g = grad / jnp.maximum(1.0, g_norm / clip)
    key = jax.random.key(seed) if seed else ctx.rng()
    noise = jax.random.normal(key, param.shape, dtype=param.dtype) * sigma * clip
    return param - _lr(lr) * (g + noise / batch_size)


@register_op("dgc", inputs=("U", "V", "Grad"),
             outputs=("UOut", "VOut", "EncodeGrad", "GradOut"),
             attrs={"m": 0.9, "ratio": 0.001, "use_nesterov": False,
                    "rampup_begin_step": 0.0, "rampup_step": 0.0,
                    "current_step": 0.0},
             grad_maker=None)
def dgc(ctx, u, v, grad, m=0.9, ratio=0.001, use_nesterov=False,
        rampup_begin_step=0.0, rampup_step=0.0, current_step=0.0):
    """Deep Gradient Compression (dgc_op.h; Lin et al. 2017): momentum
    correction + local gradient accumulation + top-ratio sparsification
    with error feedback.  EncodeGrad is dense-with-zeros (the reference
    allgathers sparse (idx, val) pairs; summing dense-with-zeros over the
    ring computes the same allreduce on TPU, where the dense psum rides
    ICI).  k = max(1, ratio * numel)."""
    g = grad.astype(jnp.float32)
    u_new = m * u + g                     # momentum correction
    # nesterov variant accumulates the lookahead m*u + g (dgc_op.h)
    v_new = v + (m * u_new + g if use_nesterov else u_new)
    flat = v_new.reshape(-1)
    n = flat.shape[0]
    k = max(int(n * float(ratio)), 1)
    thr = lax.top_k(jnp.abs(flat), k)[0][-1]
    mask = (jnp.abs(v_new) >= thr).astype(g.dtype)
    encode = v_new * mask
    v_out = v_new * (1.0 - mask)          # error feedback residual
    u_out = u_new * (1.0 - mask)
    return u_out, v_out, encode, encode.astype(grad.dtype)


# -- horizontally-fused optimizer families -----------------------------------
#
# The reference fuses per-parameter optimizer ops into one kernel over
# coalesced buffers (ir/fuse_optimizer_ops_pass.cc + coalesce_tensor).
# TPU profile (round 3): 315 tiny per-weight update fusions cost ~46 ms of
# a 211 ms ResNet-50 step — each ~64 KB fusion pays a fixed launch/DMA
# cost.  The fused lowerings concatenate the flattened group into ONE
# update computation (a single elementwise pass over ~100 MB), then split
# back; emitted by ir.py fuse_optimizer_ops_pass.


def _flatten_group(tensors):
    import numpy as _np

    sizes = [int(_np.prod(t.shape)) for t in tensors]
    flat = jnp.concatenate([t.reshape(-1) for t in tensors])
    return flat, sizes


def _split_group(flat, sizes, shapes):
    outs, off = [], 0
    for n, shp in zip(sizes, shapes):
        outs.append(flat[off:off + n].reshape(shp))
        off += n
    return outs


@register_op(
    "fused_sgd",
    inputs=("Param", "Grad", "LearningRate"),
    outputs=("ParamOut",),
    duplicable_inputs=("Param", "Grad"),
    duplicable_outputs=("ParamOut",),
    grad_maker=None,
)
def fused_sgd(ctx, params, grads, lr):
    lr_ = _lr(lr).astype(params[0].dtype)
    p_flat, sizes = _flatten_group(params)
    g_flat, _ = _flatten_group([g.astype(params[0].dtype) for g in grads])
    out = p_flat - lr_ * g_flat
    return (_split_group(out, sizes, [p.shape for p in params]),)


@register_op(
    "fused_momentum",
    inputs=("Param", "Grad", "Velocity", "LearningRate"),
    outputs=("ParamOut", "VelocityOut"),
    duplicable_inputs=("Param", "Grad", "Velocity"),
    duplicable_outputs=("ParamOut", "VelocityOut"),
    attrs={"mu": 0.0, "use_nesterov": False, "regularization_method": "",
           "regularization_coeff": 0.0},
    grad_maker=None,
)
def fused_momentum(ctx, params, grads, vels, lr, mu=0.0,
                   use_nesterov=False, regularization_method="",
                   regularization_coeff=0.0):
    dt = params[0].dtype
    lr_ = _lr(lr).astype(dt)
    if regularization_method != "l2_decay":
        # the l2 fold reads p_flat anyway, so the one-pass win is gone —
        # keep that case on the jnp path (the kernel would need a second
        # read of params just to rebuild g)
        from ..pallas_kernels import adoption, fused_opt

        use_kernel, _ = adoption.decide(
            "fused_opt", flag="FLAGS_use_pallas_fused_opt",
            checks=fused_opt.fused_opt_checks(params, grads, (vels,)))
        if use_kernel:
            p_news, v_news, bf16s = fused_opt.fused_momentum_step(
                params, grads, vels, _lr(lr), mu=mu,
                use_nesterov=use_nesterov)
            fused_opt.stash_bf16_carry(ctx, bf16s)
            return (p_news, v_news)
    p_flat, sizes = _flatten_group(params)
    g_flat, _ = _flatten_group([g.astype(dt) for g in grads])
    v_flat, _ = _flatten_group(vels)
    if regularization_method == "l2_decay":
        g_flat = g_flat + regularization_coeff * p_flat
    v_new = mu * v_flat + g_flat
    if use_nesterov:
        p_new = p_flat - (g_flat + mu * v_new) * lr_
    else:
        p_new = p_flat - lr_ * v_new
    shapes = [p.shape for p in params]
    return (_split_group(p_new, sizes, shapes),
            _split_group(v_new, sizes, shapes))


@register_op(
    "fused_adam",
    inputs=("Param", "Grad", "Moment1", "Moment2", "LearningRate",
            "Beta1Pow", "Beta2Pow"),
    outputs=("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"),
    duplicable_inputs=("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                       "Beta2Pow"),
    duplicable_outputs=("ParamOut", "Moment1Out", "Moment2Out",
                        "Beta1PowOut", "Beta2PowOut"),
    attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
    grad_maker=None,
)
def fused_adam(ctx, params, grads, m1s, m2s, lr, b1pows, b2pows,
               beta1=0.9, beta2=0.999, epsilon=1e-8):
    dt = params[0].dtype
    lr_ = _lr(lr).astype(dt)
    from ..pallas_kernels import adoption, fused_opt

    use_kernel, _ = adoption.decide(
        "fused_opt", flag="FLAGS_use_pallas_fused_opt",
        checks=fused_opt.fused_opt_checks(params, grads, (m1s, m2s)))
    if use_kernel:
        # one VMEM pass per tile: moments + AXPY + the bf16 carry cast —
        # bitwise-equal to the jnp path below (fused_opt.py docstring),
        # verified over 3 steps by tests/test_pallas_blocks.py
        p_news, m1ns, m2ns, b1outs, b2outs, bf16s = \
            fused_opt.fused_adam_step(
                params, grads, m1s, m2s, _lr(lr), b1pows, b2pows,
                beta1=beta1, beta2=beta2, epsilon=epsilon)
        fused_opt.stash_bf16_carry(ctx, bf16s)
        return (p_news, m1ns, m2ns, b1outs, b2outs)
    b1 = jnp.asarray(beta1, dt)
    b2 = jnp.asarray(beta2, dt)
    sizes = [int(np.prod(p.shape)) for p in params]
    g_flat, _ = _flatten_group([g.astype(dt) for g in grads])
    m1_flat, _ = _flatten_group(m1s)
    m2_flat, _ = _flatten_group(m2s)
    m1n = b1 * m1_flat + (1.0 - b1) * g_flat
    m2n = b2 * m2_flat + (1.0 - b2) * g_flat * g_flat
    u_flat = m1n / (jnp.sqrt(m2n) + epsilon)
    # The moment recurrences run as ONE flat elementwise pass (the launch
    # savings the fusion exists for), but the final AXPY applies per-member
    # against the ORIGINAL unconcatenated params.  This drops the p_flat
    # concat, the group-sized lr_t broadcast concat (~param-bytes of pure
    # HBM traffic each at BERT scale: one full extra read+write of the
    # parameter set), and the p_new split copies, while staying bitwise
    # identical — lr_t is piecewise-constant per member, and each ParamOut
    # slice is the same elementwise expression either way.  Per-member
    # bias correction is kept: beta-pow accumulators may diverge (param
    # added mid-training, partial checkpoint restore), so each param gets
    # ITS OWN scalar lr_t — exact parity with the unfused ops.
    p_news, off = [], 0
    for p, b1pow, b2pow, n in zip(params, b1pows, b2pows, sizes):
        b1p = b1pow.reshape(()).astype(dt)
        b2p = b2pow.reshape(()).astype(dt)
        lr_t = lr_ * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
        p_news.append(p - lr_t * u_flat[off:off + n].reshape(p.shape))
        off += n
    shapes = [p.shape for p in params]
    return (p_news,
            _split_group(m1n, sizes, shapes),
            _split_group(m2n, sizes, shapes),
            [(b.reshape(()) * b1).reshape(b.shape) for b in b1pows],
            [(b.reshape(()) * b2).reshape(b.shape) for b in b2pows])
