"""Shared helpers for op lowerings."""

import numpy as np
import jax.numpy as jnp

# fluid VarType dtype enum (framework.proto:107-125) -> dtype name, kept so
# programs/attrs using integer dtype codes stay compatible.
_DTYPE_ENUM = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    19: "int64",  # SIZE_T
    20: "uint8",
    21: "int8",
    22: "bfloat16",
}
_DTYPE_TO_ENUM = {
    "bool": 0,
    "int16": 1,
    "int32": 2,
    "int64": 3,
    "float16": 4,
    "float32": 5,
    "float64": 6,
    "uint8": 20,
    "int8": 21,
    "bfloat16": 22,
}


def attr_dtype(v, default="float32"):
    """Normalize a dtype attr (int enum / str / np dtype) to a jnp dtype."""
    from ..framework import dtype_to_np

    if v is None:
        return dtype_to_np(default)
    if isinstance(v, (int, np.integer)):
        return dtype_to_np(_DTYPE_ENUM[int(v)])
    from ..framework import convert_np_dtype_to_dtype_

    return dtype_to_np(convert_np_dtype_to_dtype_(v))


def dtype_enum(name):
    return _DTYPE_TO_ENUM[name]


def bcast_y(x, y, axis=-1):
    """Fluid elementwise broadcast semantics (elementwise_op.h): align y's
    dims to a contiguous run of x's dims starting at `axis` (axis=-1 means
    rightmost alignment); trailing unit dims of y are squeezed first."""
    if x.shape == y.shape or y.ndim == 0:
        return y
    yshape = list(y.shape)
    while len(yshape) > 1 and yshape[-1] == 1:
        yshape.pop()
    ax = x.ndim - len(yshape) if axis == -1 else axis
    new_shape = [1] * ax + yshape + [1] * (x.ndim - ax - len(yshape))
    return jnp.reshape(y, new_shape)
