"""Shared helpers for op lowerings."""

import numpy as np
import jax
import jax.numpy as jnp

# fluid VarType dtype enum (framework.proto:107-125) -> dtype name, kept so
# programs/attrs using integer dtype codes stay compatible.
_DTYPE_ENUM = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    19: "int64",  # SIZE_T
    20: "uint8",
    21: "int8",
    22: "bfloat16",
}
_DTYPE_TO_ENUM = {
    "bool": 0,
    "int16": 1,
    "int32": 2,
    "int64": 3,
    "float16": 4,
    "float32": 5,
    "float64": 6,
    "uint8": 20,
    "int8": 21,
    "bfloat16": 22,
}


def attr_dtype(v, default="float32"):
    """Normalize a dtype attr (int enum / str / np dtype) to a jnp dtype."""
    from ..framework import dtype_to_np

    if v is None:
        return dtype_to_np(default)
    if isinstance(v, (int, np.integer)):
        return dtype_to_np(_DTYPE_ENUM[int(v)])
    from ..framework import convert_np_dtype_to_dtype_

    return dtype_to_np(convert_np_dtype_to_dtype_(v))


def dtype_enum(name):
    return _DTYPE_TO_ENUM[name]


def bcast_y(x, y, axis=-1):
    """Fluid elementwise broadcast semantics (elementwise_op.h): align y's
    dims to a contiguous run of x's dims starting at `axis` (axis=-1 means
    rightmost alignment); trailing unit dims of y are squeezed first."""
    if x.shape == y.shape or y.ndim == 0:
        return y
    yshape = list(y.shape)
    while len(yshape) > 1 and yshape[-1] == 1:
        yshape.pop()
    ax = x.ndim - len(yshape) if axis == -1 else axis
    new_shape = [1] * ax + yshape + [1] * (x.ndim - ax - len(yshape))
    return jnp.reshape(y, new_shape)


def realized_prob(keep_prob):
    """The keep probability bernoulli_bytes actually samples with:
    round(keep_prob*256)/256, clamped to [0, 1].  Use wherever the
    SAMPLING distribution matters (e.g. the downgrade_in_infer inference
    multiply); realized_keep_prob below is the NaN-guarded DIVISOR
    variant."""
    return min(max(int(round(float(keep_prob) * 256.0)), 0), 256) / 256.0


def realized_keep_prob(keep_prob):
    """The keep probability bernoulli_bytes actually samples with —
    round(keep_prob*256)/256 — as a SCALE DIVISOR: clamped to >= 1/256 so
    the degenerate all-dropped draw (thr=0, mask all zero) yields exact
    zero upscaled outputs/grads instead of 0/0 = NaN.  Use for dropout's
    upscale divisor so E[out] = x holds exactly under the quantized
    draw."""
    thr = int(round(float(keep_prob) * 256.0))
    return min(max(thr, 1), 256) / 256.0


def bernoulli_bytes(key, keep_prob, shape):
    """Keep-mask sampling for dropout at ~1/4 the threefry cost.

    jax.random.bernoulli hashes one u32 counter per ELEMENT; on TPU the
    threefry bit-twiddling dominates the dropout epilogues fused into the
    surrounding matmuls (round-4 profile: ~30 ms of a 285 ms BERT step).
    Here one u32 yields four mask BYTES: byte < round(keep_prob*256) keeps
    with probability round(keep_prob*256)/256 — a <=1/512 absolute
    quantization of the keep probability, statistically immaterial for
    dropout regularization (the reference's float-compare draw has its own
    f32 rounding).  Deterministic for a given key, like bernoulli.
    """
    thr = int(round(float(keep_prob) * 256.0))
    if not all(isinstance(d, (int, np.integer)) and d >= 0 for d in shape):
        # symbolic dims (graph-build shape inference) take the reference
        # per-element draw — with the same REALIZED prob as the byte path
        # so callers' realized_keep_prob divisor matches either way
        return jax.random.bernoulli(key, realized_prob(keep_prob), shape)
    n = 1
    for d in shape:
        n *= int(d)
    if thr >= 256:
        return jnp.ones(shape, bool)
    if thr <= 0:
        return jnp.zeros(shape, bool)
    if shape and shape[-1] % 4 == 0:
        # draw in the target shape so the u32->u8 bitcast is a pure
        # minor-dim reshape (the flat draw + slice below materializes
        # copies of the whole mask)
        words = jax.random.bits(
            key, tuple(shape[:-1]) + (shape[-1] // 4,), jnp.uint32)
        by = jax.lax.bitcast_convert_type(words, jnp.uint8)
        by = by.reshape(tuple(shape))
        return by < jnp.uint8(thr)
    nw = (n + 3) // 4
    words = jax.random.bits(key, (nw,), jnp.uint32)
    by = jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(-1)
    return (by < jnp.uint8(thr))[:n].reshape(shape)
