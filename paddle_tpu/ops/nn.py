"""NN ops: conv2d, pooling, batch/layer/group/instance norm, dropout,
interpolation.

Parity: conv_op.cc (+conv_cudnn_op.cu), pool_op.cc, batch_norm_op.cc,
layer_norm_op.cc, group_norm_op.cc, instance_norm_op.cc, dropout_op.cc,
label_smooth_op.cc, interpolate_op.cc, unfold_op.cc, pixel_shuffle_op.cc
(paddle/fluid/operators/).  Convs lower to lax.conv_general_dilated (MXU);
norms are jnp compositions XLA fuses; dropout uses functional PRNG with an
explicit Mask output so the grad replays exactly.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import GradOpDesc, register_op
from ..framework import _grad_var_name
from .common import (attr_dtype, bernoulli_bytes, dtype_enum,
                     realized_keep_prob)


# -- conv --------------------------------------------------------------------


def _conv_dims(data_format):
    # Filters are always OIHW (the layer API creates them that way, so
    # checkpoints are layout-independent); only the activation layout varies.
    if data_format in ("NCHW", "AnyLayout"):
        return ("NCHW", "OIHW", "NCHW")
    return ("NHWC", "OIHW", "NHWC")


@register_op(
    "conv2d",
    inputs=("Input", "Filter"),
    outputs=("Output",),
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "data_format": "NCHW", "padding_algorithm": "EXPLICIT",
           "use_cudnn": True, "use_mkldnn": False, "fuse_relu_before_depthwise_conv": False,
           "workspace_size_MB": 512, "exhaustive_search": False},
)
def conv2d(ctx, x, w, strides=(1, 1), paddings=(0, 0), dilations=(1, 1),
           groups=1, data_format="NCHW", padding_algorithm="EXPLICIT", **_):
    if padding_algorithm == "SAME":
        pad = "SAME"
    elif padding_algorithm == "VALID":
        pad = "VALID"
    else:
        p = list(paddings)
        if len(p) == 2:
            pad = [(p[0], p[0]), (p[1], p[1])]
        else:  # [top, bottom, left, right]
            pad = [(p[0], p[1]), (p[2], p[3])]
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _conv_dims(data_format))
    # AMP: bf16 operands (MXU accumulates f32 internally), cast up after —
    # keeping operand/cotangent dtypes uniform so the conv transpose rule
    # stays well-typed under vjp
    amp = ctx is not None and ctx.amp_bf16() and x.dtype in (
        jnp.float32, jnp.bfloat16)
    xc, wc = (x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)) if amp else (x, w)
    out = lax.conv_general_dilated(
        xc, wc,
        window_strides=tuple(strides),
        padding=pad,
        rhs_dilation=tuple(dilations),
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    # bf16-carry policy: under AMP the activation stays bf16 (weights remain
    # f32 master copies); without AMP preserve the input dtype
    return out if amp else out.astype(x.dtype)


@register_op(
    "depthwise_conv2d",
    inputs=("Input", "Filter"),
    outputs=("Output",),
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "data_format": "NCHW", "padding_algorithm": "EXPLICIT",
           "use_cudnn": False},
)
def depthwise_conv2d(ctx, x, w, strides=(1, 1), paddings=(0, 0),
                     dilations=(1, 1), groups=1, data_format="NCHW",
                     padding_algorithm="EXPLICIT", **_):
    return conv2d(ctx, x, w, strides, paddings, dilations, groups,
                  data_format, padding_algorithm)


def _transpose_conv_filter(w, groups, spatial_axes):
    """Fluid transpose-conv filter [C_in, F/g, *k] -> grouped forward-conv
    filter [F, C_in/g, *k] (flipped spatially).  groups=1 reduces to the
    classic flip+swapaxes; groups>1 needs the block regrouping or
    feature_group_count rejects the shape."""
    wf = jnp.flip(w, axis=spatial_axes)
    if groups == 1:
        return jnp.swapaxes(wf, 0, 1)
    c_in, f_per_g = wf.shape[0], wf.shape[1]
    k = wf.shape[2:]
    wg = wf.reshape((groups, c_in // groups, f_per_g) + k)
    wg = jnp.swapaxes(wg, 1, 2)  # [g, F/g, C_in/g, *k]
    return wg.reshape((groups * f_per_g, c_in // groups) + k)


def _transpose_conv_extra_pad(in_sizes, k_sizes, strides, pads, dilations,
                              output_size):
    """Per-dim extra high-side padding so the lhs-dilated conv emits
    exactly `output_size` (the stride>1 inverse is ambiguous; the
    reference uses output_size/output_padding to disambiguate —
    conv_transpose_op.cc)."""
    extras = []
    for i, tgt in enumerate(output_size):
        default = ((in_sizes[i] - 1) * strides[i] - pads[i][0] - pads[i][1]
                   + dilations[i] * (k_sizes[i] - 1) + 1)
        extra = int(tgt) - default
        if extra < 0 or extra >= strides[i]:
            raise ValueError(
                "output_size[%d]=%s unreachable (valid range [%d, %d))"
                % (i, tgt, default, default + strides[i]))
        extras.append(extra)
    return extras


@register_op(
    "conv2d_transpose",
    inputs=("Input", "Filter"),
    outputs=("Output",),
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "data_format": "NCHW", "output_size": [],
           "padding_algorithm": "EXPLICIT", "use_cudnn": True},
)
def conv2d_transpose(ctx, x, w, strides=(1, 1), paddings=(0, 0),
                     dilations=(1, 1), groups=1, data_format="NCHW",
                     output_size=(), padding_algorithm="EXPLICIT", **_):
    # filter layout IOHW (fluid conv2d_transpose: [in_c, out_c/g, kh, kw])
    p = list(paddings)
    pads = [(p[0], p[0]), (p[1], p[1])] if len(p) == 2 else [
        (p[0], p[1]), (p[2], p[3])
    ]
    kh, kw = w.shape[2], w.shape[3]
    sh, sw = strides
    dil = list(dilations)
    extra = [0, 0]
    if output_size:
        extra = _transpose_conv_extra_pad(
            (x.shape[2], x.shape[3]), (kh, kw), (sh, sw), pads, dil,
            output_size)
    # transpose conv = lhs-dilated conv with flipped kernel
    wt = _transpose_conv_filter(w, groups, (2, 3))
    dn = lax.conv_dimension_numbers(x.shape, wt.shape, ("NCHW", "OIHW", "NCHW"))
    out = lax.conv_general_dilated(
        x, wt,
        window_strides=(1, 1),
        padding=[(kh - 1 - pads[0][0], kh - 1 - pads[0][1] + extra[0]),
                 (kw - 1 - pads[1][0], kw - 1 - pads[1][1] + extra[1])],
        lhs_dilation=(sh, sw),
        rhs_dilation=tuple(dil),
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    return out


# -- pooling -----------------------------------------------------------------


@register_op(
    "pool2d",
    inputs=("X",),
    outputs=("Out",),
    attrs={"pooling_type": "max", "ksize": [1, 1], "strides": [1, 1],
           "paddings": [0, 0], "global_pooling": False, "ceil_mode": False,
           "exclusive": True, "adaptive": False, "data_format": "NCHW",
           "padding_algorithm": "EXPLICIT", "use_cudnn": True},
)
def pool2d(ctx, x, pooling_type="max", ksize=(1, 1), strides=(1, 1),
           paddings=(0, 0), global_pooling=False, ceil_mode=False,
           exclusive=True, adaptive=False, data_format="NCHW", **_):
    nchw = data_format in ("NCHW", "AnyLayout")
    h_ax, w_ax = (2, 3) if nchw else (1, 2)
    if global_pooling:
        if pooling_type == "max":
            return jnp.max(x, axis=(h_ax, w_ax), keepdims=True)
        return jnp.mean(x, axis=(h_ax, w_ax), keepdims=True)
    if adaptive:
        oh, ow = int(ksize[0]), int(ksize[1])
        H, W = x.shape[h_ax], x.shape[w_ax]
        if H % oh == 0 and W % ow == 0:
            fh, fw = H // oh, W // ow
            if nchw:
                r = x.reshape(x.shape[0], x.shape[1], oh, fh, ow, fw)
                return (jnp.max(r, axis=(3, 5)) if pooling_type == "max"
                        else jnp.mean(r, axis=(3, 5)))
            r = x.reshape(x.shape[0], oh, fh, ow, fw, x.shape[3])
            return (jnp.max(r, axis=(2, 4)) if pooling_type == "max"
                    else jnp.mean(r, axis=(2, 4)))
        # arbitrary output sizes (reference pooling.h AdaptStartIndex/
        # AdaptEndIndex: start = floor(i*I/O), end = ceil((i+1)*I/O)).
        # Bin boundaries are Python ints at trace time, so this stays
        # static-shaped: one slice-reduce per output cell, fused by XLA.
        red = jnp.max if pooling_type == "max" else jnp.mean
        rows = []
        for i in range(oh):
            hs, he = (i * H) // oh, -((-(i + 1) * H) // oh)
            cols = []
            for j in range(ow):
                ws, we = (j * W) // ow, -((-(j + 1) * W) // ow)
                if nchw:
                    patch = x[:, :, hs:he, ws:we]
                    cols.append(red(patch, axis=(2, 3)))
                else:
                    patch = x[:, hs:he, ws:we, :]
                    cols.append(red(patch, axis=(1, 2)))
            rows.append(jnp.stack(cols, axis=-1 if nchw else 1))
        if nchw:
            return jnp.stack(rows, axis=2)  # [N, C, oh, ow]
        return jnp.stack(rows, axis=1)      # [N, oh, ow, C]

    kh, kw = int(ksize[0]), int(ksize[1])
    sh, sw = int(strides[0]), int(strides[1])
    ph, pw = int(paddings[0]), int(paddings[1])
    if ceil_mode:
        H, W = x.shape[h_ax], x.shape[w_ax]
        extra_h = -(H + 2 * ph - kh) % sh
        extra_w = -(W + 2 * pw - kw) % sw
        pad_h = (ph, ph + extra_h)
        pad_w = (pw, pw + extra_w)
    else:
        pad_h, pad_w = (ph, ph), (pw, pw)
    if nchw:
        window = (1, 1, kh, kw)
        strides_ = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), pad_h, pad_w)
    else:
        window = (1, kh, kw, 1)
        strides_ = (1, sh, sw, 1)
        pads = ((0, 0), pad_h, pad_w, (0, 0))
    # NB: init values must be Python scalars for JAX to select the
    # differentiable reduce_window_{max,sum} primitives
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else int(
            jnp.iinfo(x.dtype).min)
        return lax.reduce_window(x, init, lax.max, window, strides_, pads)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_, pads)
    if exclusive and (pad_h != (0, 0) or pad_w != (0, 0)):
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides_, pads)
        return s / cnt
    return s / (kh * kw)


# -- normalization -----------------------------------------------------------


def _bn_impl(x, scale, bias, mean, variance, axes, cshape, momentum,
             epsilon, use_stored_stats, axis_name=None, stat_subsample=1):
    """Shared batch_norm / sync_batch_norm body: f32 statistics (optionally
    pmean'd over the data-parallel axis — the reference's in-kernel
    ncclAllReduce, sync_batch_norm_op.cu), bf16-carry output.

    stat_subsample>1 estimates the batch statistics from every k-th sample
    (ghost batch norm).  On bandwidth-starved devices the statistics passes
    re-read every conv output at the reduction-bandwidth cap, so this
    directly cuts the dominant HBM traffic; statistically it is the
    well-studied small-ghost-batch estimator (neutral-to-helpful at large
    batch).  Default 1 = exact reference semantics."""
    if use_stored_stats:
        m, v = mean, variance
        new_mean, new_var = mean, variance
    else:
        if stat_subsample > 1 and isinstance(x.shape[0], int):
            # contiguous prefix (batches are shuffled): a strided slice on
            # the sublane-packed batch axis costs more than it saves.  The
            # int guard keeps symbolic-batch shape inference on the exact
            # path (stat shapes do not depend on the subsample).  Slice the
            # carry-dtype tensor BEFORE the f32 convert so the full-size
            # f32 copy is never materialized.
            xs = x[: max(x.shape[0] // stat_subsample, 1)].astype(jnp.float32)
        else:
            xs = x.astype(jnp.float32)
        m = jnp.mean(xs, axis=axes)
        msq = jnp.mean(jnp.square(xs), axis=axes)
        if axis_name is not None:
            # cross-replica moments: mean of means is exact for equal shards
            m = lax.pmean(m, axis_name)
            msq = lax.pmean(msq, axis_name)
        v = msq - jnp.square(m)
        new_mean = momentum * mean + (1 - momentum) * m
        new_var = momentum * variance + (1 - momentum) * v
    inv = 1.0 / jnp.sqrt(v + epsilon)
    # fold the normalization into one per-channel affine computed in f32 and
    # applied in the carry dtype: the big-tensor pass is a single bf16
    # multiply-add instead of sub/mul/mul/add in f32 (the elementwise BN
    # passes are pure HBM-bandwidth + VPU cost, ~20% of a ResNet-50 step)
    a = (inv * scale).reshape(cshape)
    b = (bias - m * inv * scale).reshape(cshape)
    y = x * a.astype(x.dtype) + b.astype(x.dtype)
    return (y, new_mean, new_var, m, inv, None)


def _bn_grad_maker(op, no_grad_set):
    """batch_norm grad: differentiate through Y only (running stats are
    stop-gradient); uses SavedMean/SavedVariance like batch_norm_grad op."""
    inputs = {
        "X": list(op.input("X")),
        "Scale": list(op.input("Scale")),
        "Bias": list(op.input("Bias")),
        "SavedMean": list(op.output("SavedMean")),
        "SavedVariance": list(op.output("SavedVariance")),
        "GRAD@Y": [_grad_var_name(op.output("Y")[0])],
    }
    outputs = {}
    for slot in ("X", "Scale", "Bias"):
        n = op.input(slot)[0]
        if n not in no_grad_set:
            outputs["X@" + slot] = [_grad_var_name(n)]
    if not outputs:
        return []
    return [GradOpDesc("batch_norm_grad", inputs, outputs, dict(op.attrs))]


@register_op(
    "batch_norm",
    inputs=("X", "Scale", "Bias", "Mean", "Variance"),
    outputs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance",
             "ReserveSpace"),
    attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
           "data_layout": "NCHW", "use_global_stats": False,
           "trainable_statistics": False, "fuse_with_relu": False,
           "stat_subsample": 1},
    grad_maker=_bn_grad_maker,
)
def batch_norm(ctx, x, scale, bias, mean, variance, momentum=0.9,
               epsilon=1e-5, is_test=False, data_layout="NCHW",
               use_global_stats=False, stat_subsample=1, **_):
    nchw = data_layout in ("NCHW", "AnyLayout")
    axes = (0, 2, 3) if (nchw and x.ndim == 4) else tuple(
        i for i in range(x.ndim) if i != (1 if nchw else x.ndim - 1)
    )
    cshape = [1] * x.ndim
    c_ax = 1 if nchw else x.ndim - 1
    cshape[c_ax] = x.shape[c_ax]

    return _bn_impl(x, scale, bias, mean, variance, axes, cshape, momentum,
                    epsilon, is_test or use_global_stats, axis_name=None,
                    stat_subsample=int(stat_subsample))


@register_op(
    "batch_norm_grad",
    inputs=("X", "Scale", "Bias", "SavedMean", "SavedVariance", "GRAD@Y"),
    outputs=("X@X", "X@Scale", "X@Bias"),
    attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
           "data_layout": "NCHW", "use_global_stats": False},
    grad_maker=None,
    optional_inputs=("GRAD@Y",),
)
def batch_norm_grad(ctx, x, scale, bias, saved_mean, saved_inv_std, dy,
                    momentum=0.9, epsilon=1e-5, is_test=False,
                    data_layout="NCHW", use_global_stats=False, **_):
    nchw = data_layout in ("NCHW", "AnyLayout")
    c_ax = 1 if nchw else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_ax)
    cshape = [1] * x.ndim
    cshape[c_ax] = x.shape[c_ax]
    if dy is None:
        dy = jnp.zeros_like(x)
    n = 1
    for i in axes:
        n *= x.shape[i]
    f32 = jnp.float32
    mu = saved_mean.reshape(cshape).astype(f32)
    inv = saved_inv_std.reshape(cshape).astype(f32)
    # reductions promote to f32 inside the fused reduce (reads stay bf16)
    dyf = dy.astype(f32)
    xhatf = (x.astype(f32) - mu) * inv
    dscale = jnp.sum(dyf * xhatf, axis=axes)
    dbias = jnp.sum(dyf, axis=axes)
    s = scale.astype(f32)
    if is_test or use_global_stats:
        a1 = (s.reshape(cshape) * inv)
        dx = dy * a1.astype(x.dtype)
    else:
        # dx = s*inv/n * (n*dy - dbias - xhat*dscale) rearranged into one
        # per-channel affine a1*dy + a2*x + a3 applied in the carry dtype
        # (same bandwidth-motivated folding as the forward)
        sinv = s.reshape(cshape) * inv
        a1 = sinv
        a2 = -sinv * inv * dscale.reshape(cshape) / n
        a3 = (-sinv * dbias.reshape(cshape)
              + sinv * inv * dscale.reshape(cshape) * mu) / n
        dx = (dy * a1.astype(x.dtype) + x * a2.astype(x.dtype)
              + a3.astype(x.dtype))
    return dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype)


@register_op(
    "conv2d_bn_relu",
    inputs=("Input", "Filter", "Scale", "Bias", "Mean", "Variance"),
    outputs=("Output", "MeanOut", "VarianceOut", "SavedMean",
             "SavedVariance"),
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "data_format": "NCHW", "momentum": 0.9,
           "epsilon": 1e-5, "is_test": False, "with_relu": True},
    no_grad_inputs=("Mean", "Variance"),
)
def conv2d_bn_relu(ctx, x, w, scale, bias, mean, variance, strides=(1, 1),
                   paddings=(0, 0), dilations=(1, 1), groups=1,
                   data_format="NCHW", momentum=0.9, epsilon=1e-5,
                   is_test=False, with_relu=True, **_):
    """Fused conv + batch-norm (+ relu) trunk block — the reference's
    conv_bn_fuse_pass / conv2d_fusion analogue.  Routes to the Pallas
    block kernel when FLAGS_use_pallas_conv_block + eligibility + the
    probe gate all pass (pallas_kernels/adoption.py); otherwise lowers to
    the exact conv2d + _bn_impl (+ relu) composition, so the op is safe to
    emit unconditionally.  SavedVariance holds the INVERSE std, mirroring
    batch_norm.  Gradients come from the auto grad maker (jax.vjp over
    this lowering; the kernel path carries a custom_vjp that routes its
    backward through the reference composition)."""
    from ..pallas_kernels import adoption, conv_block

    f32 = jnp.float32
    checks = conv_block.conv_block_checks(
        x.shape, w.shape, strides, paddings, dilations, groups, data_format,
        jnp.dtype(x.dtype).itemsize)
    use_kernel, _ = adoption.decide(
        "conv_block", flag="FLAGS_use_pallas_conv_block", checks=checks)
    if use_kernel:
        stride, pad = int(strides[0]), int(paddings[0])
        if is_test:
            y = conv_block.conv_bn_relu_inference(
                x, w, scale, bias, mean, variance, epsilon, stride, pad,
                bool(with_relu))
            m, v = mean.astype(f32), variance.astype(f32)
            new_mean, new_var = mean, variance
        else:
            y, m, v = conv_block.conv_bn_relu_train(
                x, w, scale, bias, epsilon, stride, pad, bool(with_relu))
            new_mean = momentum * mean + (1 - momentum) * m.astype(mean.dtype)
            new_var = momentum * variance + (1 - momentum) * v.astype(
                variance.dtype)
        inv = 1.0 / jnp.sqrt(v + epsilon)
        return y, new_mean, new_var, m, inv
    # fallback: the general composition (any stride/padding/dilation/groups,
    # AMP handled by the conv2d lowering)
    conv = conv2d(ctx, x, w, strides, paddings, dilations, groups,
                  data_format)
    nchw = data_format in ("NCHW", "AnyLayout")
    c_ax = 1 if nchw else conv.ndim - 1
    axes = tuple(i for i in range(conv.ndim) if i != c_ax)
    cshape = [1] * conv.ndim
    cshape[c_ax] = conv.shape[c_ax]
    y, new_mean, new_var, m, inv, _r = _bn_impl(
        conv, scale, bias, mean, variance, axes, cshape, momentum, epsilon,
        is_test)
    if with_relu:
        y = jnp.maximum(y, jnp.zeros((), y.dtype))
    return y, new_mean, new_var, m, inv


@register_op(
    "layer_norm",
    inputs=("X", "Scale", "Bias"),
    outputs=("Y", "Mean", "Variance"),
    attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
    optional_inputs=("Scale", "Bias"),
)
def layer_norm(ctx, x, scale, bias, epsilon=1e-5, begin_norm_axis=1):
    import numpy as _np

    lead = x.shape[:begin_norm_axis]
    tail = x.shape[begin_norm_axis:]
    # symbolic dims (shape inference's eval_shape) must stay clear of the
    # int-only np.prod below — they take the jnp composition branch
    concrete = all(isinstance(d, int) and d > 0 for d in x.shape)
    if concrete and scale is not None and bias is not None:
        from ..pallas_kernels import adoption
        from ..pallas_kernels.layer_norm import layer_norm_2d, ln_checks

        R = int(_np.prod(lead)) if lead else 1
        C = int(_np.prod(tail)) if tail else 1
        use_kernel, _ = adoption.decide(
            "layer_norm", flag="FLAGS_use_pallas_layer_norm",
            checks=ln_checks(R, C))
        if use_kernel:
            # fused single-pass kernel: wins standalone (5.44 vs
            # 6.27 ms at BERT shapes, f32-stat accuracy) but loses
            # in-program on the bench chip (719.7 vs 730.6 seqs/s —
            # it breaks XLA's LN-neighbor fusions), hence opt-in.
            # Mean/Variance cast to x.dtype so the op's output
            # dtypes don't depend on the flag
            y2, m2, v2 = layer_norm_2d(
                x.reshape(R, C), scale.reshape(C), bias.reshape(C),
                epsilon)
            return (y2.reshape(x.shape),
                    m2.astype(x.dtype).reshape(lead),
                    v2.astype(x.dtype).reshape(lead))
    axes = tuple(range(begin_norm_axis, x.ndim))
    # bf16 inputs (the AMP carry dtype) get f32 internal statistics — an
    # 8-bit-mantissa mean/var costs accuracy (same policy as the Pallas
    # kernel and _bn_impl); the carry dtype is restored on the outputs
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - m) / jnp.sqrt(v + epsilon)
    if scale is not None:
        y = y * scale.reshape(tail)
    if bias is not None:
        y = y + bias.reshape(tail)
    return (y.astype(x.dtype), m.astype(x.dtype).reshape(lead),
            v.astype(x.dtype).reshape(lead))


@register_op(
    "group_norm",
    inputs=("X", "Scale", "Bias"),
    outputs=("Y", "Mean", "Variance"),
    attrs={"epsilon": 1e-5, "groups": 1, "data_layout": "NCHW"},
    optional_inputs=("Scale", "Bias"),
)
def group_norm(ctx, x, scale, bias, epsilon=1e-5, groups=1,
               data_layout="NCHW"):
    N = x.shape[0]
    if data_layout == "NCHW":
        C = x.shape[1]
        r = x.reshape(N, groups, C // groups, *x.shape[2:])
        axes = tuple(range(2, r.ndim))
        m = jnp.mean(r, axis=axes, keepdims=True)
        v = jnp.var(r, axis=axes, keepdims=True)
        y = ((r - m) / jnp.sqrt(v + epsilon)).reshape(x.shape)
        cshape = (1, C) + (1,) * (x.ndim - 2)
    else:
        C = x.shape[-1]
        r = x.reshape(N, *x.shape[1:-1], groups, C // groups)
        axes = tuple(range(1, r.ndim - 2)) + (r.ndim - 1,)
        m = jnp.mean(r, axis=axes, keepdims=True)
        v = jnp.var(r, axis=axes, keepdims=True)
        y = ((r - m) / jnp.sqrt(v + epsilon)).reshape(x.shape)
        cshape = (1,) * (x.ndim - 1) + (C,)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    return y, m.reshape(N, groups), v.reshape(N, groups)


@register_op(
    "instance_norm",
    inputs=("X", "Scale", "Bias"),
    outputs=("Y", "SavedMean", "SavedVariance"),
    attrs={"epsilon": 1e-5},
    optional_inputs=("Scale", "Bias"),
)
def instance_norm(ctx, x, scale, bias, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    y = (x - m) / jnp.sqrt(v + epsilon)
    cshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    return y, jnp.squeeze(m, axes), 1.0 / jnp.sqrt(jnp.squeeze(v, axes) + epsilon)


@register_op(
    "norm",
    inputs=("X",),
    outputs=("Norm", "Out"),
    attrs={"axis": 1, "epsilon": 1e-10},
)
def norm(ctx, x, axis=1, epsilon=1e-10):
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + epsilon)
    return norm, x / norm


# -- dropout -----------------------------------------------------------------


def _dropout_grad_maker(op, no_grad_set):
    x = op.input("X")[0]
    if x in no_grad_set:
        return []
    return [
        GradOpDesc(
            "dropout_grad",
            {"Mask": list(op.output("Mask")),
             "GRAD@Out": [_grad_var_name(op.output("Out")[0])]},
            {"X@X": [_grad_var_name(x)]},
            dict(op.attrs),
        )
    ]


@register_op(
    "dropout",
    inputs=("X",),
    outputs=("Out", "Mask"),
    attrs={"dropout_prob": 0.5, "is_test": False, "fix_seed": False,
           "seed": 0, "dropout_implementation": "downgrade_in_infer"},
    grad_maker=_dropout_grad_maker,
    n_rng=1,
)
def dropout(ctx, x, dropout_prob=0.5, is_test=False, fix_seed=False, seed=0,
            dropout_implementation="downgrade_in_infer", **_):
    if is_test:
        if dropout_implementation == "upscale_in_train":
            return x, jnp.ones_like(x, dtype=jnp.uint8)
        # downgrade inference scales by the NOMINAL (1-p) — exact reference
        # parity for imported models (no sampling happens at inference, so
        # nothing forces the quantized grid here).  Known asymmetry: the
        # TRAIN side masks with the 256-quantized realized keep prob, so
        # E[train out] and this infer out differ by up to 2^-9 relative —
        # inference parity is deliberately preferred over expectation
        # consistency (ADVICE round 5).
        return (x * (1.0 - dropout_prob),
                jnp.ones_like(x, dtype=jnp.uint8))
    # training scale factors use the REALIZED keep probability of the
    # quantized byte draw (round(keep*256)/256) so E[out] = x exactly
    q = realized_keep_prob(1.0 - dropout_prob)
    key = jax.random.key(seed) if fix_seed else ctx.rng()
    keep = bernoulli_bytes(key, 1.0 - dropout_prob, x.shape)
    mask = keep.astype(jnp.uint8)
    if dropout_implementation == "upscale_in_train":
        out = jnp.where(keep, x / q, 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return out, mask


@register_op(
    "dropout_grad",
    inputs=("Mask", "GRAD@Out"),
    outputs=("X@X",),
    attrs={"dropout_prob": 0.5, "is_test": False, "fix_seed": False,
           "seed": 0, "dropout_implementation": "downgrade_in_infer"},
    grad_maker=None,
)
def dropout_grad(ctx, mask, dy, dropout_prob=0.5, is_test=False,
                 dropout_implementation="downgrade_in_infer", **_):
    m = mask.astype(dy.dtype)
    if dropout_implementation == "upscale_in_train":
        # same realized-keep divisor as the forward (see dropout)
        return dy * m / realized_keep_prob(1.0 - dropout_prob)
    return dy * m


@register_op(
    "label_smooth",
    inputs=("X", "PriorDist"),
    outputs=("Out",),
    attrs={"epsilon": 0.1},
    optional_inputs=("PriorDist",),
)
def label_smooth(ctx, x, prior, epsilon=0.1):
    k = x.shape[-1]
    if prior is not None:
        return (1.0 - epsilon) * x + epsilon * prior.reshape((1,) * (x.ndim - 1) + (k,))
    return (1.0 - epsilon) * x + epsilon / k


# -- interpolation / layout --------------------------------------------------


def _interp(x, out_h, out_w, method, data_layout):
    nchw = data_layout in ("NCHW", "AnyLayout")
    if nchw:
        shape = (x.shape[0], x.shape[1], out_h, out_w)
    else:
        shape = (x.shape[0], out_h, out_w, x.shape[3])
    return jax.image.resize(x, shape, method=method)


@register_op(
    "bilinear_interp",
    inputs=("X", "OutSize", "SizeTensor", "Scale"),
    outputs=("Out",),
    attrs={"out_h": -1, "out_w": -1, "align_corners": True, "align_mode": 1,
           "data_layout": "NCHW", "interp_method": "bilinear", "scale": 0.0},
    optional_inputs=("OutSize", "SizeTensor", "Scale"),
    duplicable_inputs=("SizeTensor",),
)
def bilinear_interp(ctx, x, out_size, size_tensor, scale_t, out_h=-1,
                    out_w=-1, align_corners=True, align_mode=1,
                    data_layout="NCHW", scale=0.0, **_):
    if scale and out_h < 0:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return _interp(x, out_h, out_w, "bilinear", data_layout)


@register_op(
    "nearest_interp",
    inputs=("X", "OutSize", "SizeTensor", "Scale"),
    outputs=("Out",),
    attrs={"out_h": -1, "out_w": -1, "align_corners": True,
           "data_layout": "NCHW", "interp_method": "nearest", "scale": 0.0},
    optional_inputs=("OutSize", "SizeTensor", "Scale"),
    duplicable_inputs=("SizeTensor",),
)
def nearest_interp(ctx, x, out_size, size_tensor, scale_t, out_h=-1,
                   out_w=-1, align_corners=True, data_layout="NCHW",
                   scale=0.0, **_):
    if scale and out_h < 0:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return _interp(x, out_h, out_w, "nearest", data_layout)


@register_op(
    "unfold",
    inputs=("X",),
    outputs=("Y",),
    attrs={"kernel_sizes": [1, 1], "strides": [1, 1],
           "paddings": [0, 0, 0, 0], "dilations": [1, 1]},
)
def unfold(ctx, x, kernel_sizes=(1, 1), strides=(1, 1),
           paddings=(0, 0, 0, 0), dilations=(1, 1)):
    patches = lax.conv_general_dilated_patches(
        x,
        filter_shape=tuple(kernel_sizes),
        window_strides=tuple(strides),
        padding=[(paddings[0], paddings[2]), (paddings[1], paddings[3])],
        rhs_dilation=tuple(dilations),
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, 1) + tuple(kernel_sizes), ("NCHW", "OIHW", "NCHW")
        ),
    )
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


@register_op(
    "pixel_shuffle",
    inputs=("X",),
    outputs=("Out",),
    attrs={"upscale_factor": 1},
)
def pixel_shuffle(ctx, x, upscale_factor=1):
    n, c, h, w = x.shape
    r = upscale_factor
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    return out.reshape(n, c // (r * r), h * r, w * r)


@register_op(
    "uniform_random_batch_size_like",
    inputs=("Input",),
    outputs=("Out",),
    attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
           "min": -1.0, "max": 1.0, "seed": 0, "dtype": 5},
    grad_maker=None,
    n_rng=1,
)
def uniform_random_batch_size_like(ctx, input, shape=(), input_dim_idx=0,
                                   output_dim_idx=0, min=-1.0, max=1.0,
                                   seed=0, dtype=5):
    out_shape = list(int(s) for s in shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    key = jax.random.key(seed) if seed else ctx.rng()
    return jax.random.uniform(key, tuple(out_shape), dtype=attr_dtype(dtype),
                              minval=min, maxval=max)


def _attention_composed(q, k, v, bias, causal, sm_scale, keep_mask=None,
                        dropout_prob=0.0, bshd=True):
    """Composed attention with optional attention-prob dropout
    (upscale_in_train) applied via an explicit KEEP MASK (so forward and
    backward share the exact same mask — cf. the dropout op's saved
    Mask).  Einsums run in the carry dtype (bf16 under AMP; the MXU
    accumulates f32 internally); softmax normalizes in f32 like
    _ref_attention.  bshd=True takes [B, S, H, D] operands transpose-free
    (dot_general batches the non-adjacent dims); bshd=False [B, H, S, D].
    """
    eq_s = "bqhd,bkhd->bhqk" if bshd else "bhqd,bhkd->bhqk"
    eq_o = "bhqk,bkhd->bqhd" if bshd else "bhqk,bhkd->bhqd"
    s = jnp.einsum(eq_s, q, k) * jnp.asarray(sm_scale, q.dtype)
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        qi = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        kj = lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(kj <= qi, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    if keep_mask is not None:
        kq = realized_keep_prob(1.0 - dropout_prob)
        p = jnp.where(keep_mask.astype(bool),
                      p / jnp.asarray(kq, p.dtype),
                      jnp.asarray(0.0, p.dtype))
    return jnp.einsum(eq_o, p, v)


def _fa_check_layout(layout):
    if layout not in ("BHSD", "BSHD"):
        raise ValueError(
            "flash_attention layout must be 'BHSD' or 'BSHD', got %r"
            % (layout,))


def _fa_uses_dropout(attrs):
    return (float(attrs.get("dropout_prob", 0.0) or 0.0) > 0.0
            and not attrs.get("is_test", False))


def _flash_attention_grad_maker(op, no_grad_set):
    inputs = {
        "Q": list(op.input("Q")),
        "K": list(op.input("K")),
        "V": list(op.input("V")),
        "Mask": list(op.output("Mask")),
        "Out": list(op.output("Out")),
        "Seed": list(op.output("Seed")),
        "Lse": list(op.output("Lse")),
        "GRAD@Out": [_grad_var_name(op.output("Out")[0])],
    }
    if op.input("BiasQK"):
        inputs["BiasQK"] = list(op.input("BiasQK"))
    outputs = {}
    for slot in ("Q", "K", "V"):
        n = op.input(slot)[0]
        if n not in no_grad_set:
            outputs["X@" + slot] = [_grad_var_name(n)]
    if not outputs:
        return []
    return [GradOpDesc("flash_attention_grad", inputs, outputs,
                       dict(op.attrs))]


def _fa_module():
    """The flash_attention MODULE — the package __init__ re-exports the
    function under the same name, so a plain from-import gets the
    function; every site needing module attributes goes through here."""
    import importlib

    return importlib.import_module(
        "paddle_tpu.pallas_kernels.flash_attention")


def _fa_small_kernel_ok(q_shape, k_shape, bias_shape, attrs):
    """Static routing predicate for the small-seq fused training kernel.
    Shared by the forward and grad lowerings: both MUST route identically
    (the grad replays the in-kernel dropout mask from Seed)."""
    import jax as _jax

    from .. import flags as _flags

    # opt-in (FLAGS_fused_small_attention): measured 18% slower in-step
    # than the composed training emission at bs224 — see flags.py note
    if not _flags.get_flags(["FLAGS_fused_small_attention"])[
            "FLAGS_fused_small_attention"]:
        return False
    _fam = _fa_module()
    if not _fa_uses_dropout(attrs):
        return False
    if _jax.default_backend() != "tpu":
        return False
    return _fam.small_attention_shapes_ok(
        q_shape, k_shape, bias_shape, attrs.get("causal", False),
        attrs.get("layout", "BHSD"))


@register_op(
    "flash_attention",
    inputs=("Q", "K", "V", "BiasQK"),
    outputs=("Out", "Mask", "Seed", "Lse"),
    attrs={"causal": False, "scale": 0.0, "layout": "BHSD",
           "dropout_prob": 0.0, "is_test": False},
    optional_inputs=("BiasQK",),
    no_grad_inputs=("BiasQK",),
    grad_maker=_flash_attention_grad_maker,
    n_rng=1,  # drawn only when dropout is active — see rng_when below
)
def flash_attention_op(ctx, q, k, v, bias_qk=None, causal=False, scale=0.0,
                       layout="BHSD", dropout_prob=0.0, is_test=False):
    """Fused blockwise attention (Pallas TPU kernel with jnp fallback).

    TPU-native replacement for the reference's fused inference attention
    (paddle/fluid/operators/fused/multihead_matmul_op.cu) — but trainable:
    the kernel carries a FlashAttention backward (pallas_kernels/
    flash_attention.py).  q/k/v: [B, H, S, D] (layout="BHSD", default) or
    [B, S, H, D] (layout="BSHD" — transpose-free: the head split is a
    plain reshape and dot_general batches over non-adjacent dims; on the
    bench chip XLA re-inserts equivalent layout copies, so this is a
    capability, not a measured win — BASELINE.md); bias_qk:
    [B, 1|H, Sq, Sk].

    dropout_prob > 0 (training mode) applies attention-prob dropout
    (upscale_in_train) inside the op via a sampled keep mask that is
    SAVED as the Mask output, so the custom backward replays with the
    exact forward mask (the dropout-op contract; an rng re-draw in the
    backward would decouple gradients from the sampled loss).  The Pallas
    kernel engages for dropout-free BHSD at the measured seq cutoff.

    BiasQK is an additive MASK, not a trainable tensor: the backward
    returns no bias cotangent, so it is registered no-grad on every
    backend.  scale=0.0 (the default) means "use 1/sqrt(head_dim)"; pass
    scale=1.0 explicitly if the scaling is already folded into q.
    """
    from ..pallas_kernels import flash_attention as _fa

    _fam = _fa_module()
    _fa_check_layout(layout)
    head_dim = q.shape[-1]
    sm_scale = scale if scale else head_dim ** -0.5
    bshd = layout == "BSHD"
    attrs = {"dropout_prob": dropout_prob, "is_test": is_test,
             "causal": causal, "layout": layout}
    seed_ph = jnp.zeros((2,), jnp.int32)
    lse_ph = jnp.zeros((1, 1, 1, 1), jnp.float32)
    if _fa_small_kernel_ok(q.shape, k.shape,
                           None if bias_qk is None else bias_qk.shape,
                           attrs):
        # small-seq fused training kernel: bias + softmax + in-kernel
        # dropout in one pass; Seed+Lse (not a materialized mask) carry
        # the backward's replay state
        seed_arr = jax.random.bits(ctx.rng(), (2,), jnp.uint32)
        out, lse = _fam.small_attention_fwd(q, k, v, bias_qk, sm_scale,
                                            dropout_prob, seed_arr)
        return (out, jnp.zeros((1,), jnp.uint8),
                seed_arr.astype(jnp.int32), lse)
    if _fa_uses_dropout(attrs):
        B = q.shape[0]
        H = q.shape[2] if bshd else q.shape[1]
        Sq = q.shape[1] if bshd else q.shape[2]
        Sk = k.shape[1] if bshd else k.shape[2]
        keep = bernoulli_bytes(ctx.rng(), 1.0 - dropout_prob,
                               (B, H, Sq, Sk))
        out = _attention_composed(q, k, v, bias_qk, causal, sm_scale,
                                  keep, dropout_prob, bshd)
        return out, keep.astype(jnp.uint8), seed_ph, lse_ph
    mask_placeholder = jnp.zeros((1,), jnp.uint8)
    if bshd:
        return (_attention_composed(q, k, v, bias_qk, causal, sm_scale,
                                    bshd=True), mask_placeholder, seed_ph,
                lse_ph)
    return (_fa(q, k, v, bias=bias_qk, causal=causal, sm_scale=sm_scale),
            mask_placeholder, seed_ph, lse_ph)


@register_op(
    "flash_attention_grad",
    inputs=("Q", "K", "V", "BiasQK", "Mask", "Out", "Seed", "Lse",
            "GRAD@Out"),
    outputs=("X@Q", "X@K", "X@V"),
    attrs={"causal": False, "scale": 0.0, "layout": "BHSD",
           "dropout_prob": 0.0, "is_test": False},
    optional_inputs=("BiasQK",),
    grad_maker=None,
)
def flash_attention_grad_op(ctx, q, k, v, bias_qk, mask, out, seed_words,
                            lse, dy, causal=False, scale=0.0,
                            layout="BHSD", dropout_prob=0.0,
                            is_test=False):
    """Backward: the small-seq fused kernel re-draws its in-kernel mask
    from the saved Seed and recomputes probs from Lse; the composed
    dropout path replays with the SAVED Mask; the dropout-free path
    differentiates the kernel's own custom vjp.  Routing must mirror the
    forward exactly (same static predicate)."""
    from ..pallas_kernels import flash_attention as _fa

    _fam = _fa_module()
    _fa_check_layout(layout)
    sm_scale = scale if scale else q.shape[-1] ** -0.5
    bshd = layout == "BSHD"
    attrs = {"dropout_prob": dropout_prob, "is_test": is_test,
             "causal": causal, "layout": layout}
    if _fa_small_kernel_ok(q.shape, k.shape,
                           None if bias_qk is None else bias_qk.shape,
                           attrs):
        return _fam.small_attention_bwd(
            q, k, v, bias_qk, sm_scale, dropout_prob,
            seed_words.astype(jnp.uint32), out, lse, dy)
    if _fa_uses_dropout(attrs):
        fn = lambda a, b, c: _attention_composed(
            a, b, c, bias_qk, causal, sm_scale, mask, dropout_prob, bshd)
    elif bshd:
        fn = lambda a, b, c: _attention_composed(
            a, b, c, bias_qk, causal, sm_scale, bshd=True)
    else:
        fn = lambda a, b, c: _fa(a, b, c, bias=bias_qk, causal=causal,
                                 sm_scale=sm_scale)
    _, vjp = jax.vjp(fn, q, k, v)
    return vjp(dy)


flash_attention_op.opdef.rng_when = _fa_uses_dropout


def _fdaln_uses_dropout(attrs):
    return (float(attrs.get("dropout_prob", 0.0) or 0.0) > 0.0
            and not attrs.get("is_test", False))


def _fused_dropout_add_ln_grad_maker(op, no_grad_set):
    inputs = {
        "R": list(op.output("R")),
        "Scale": list(op.input("Scale")),
        "Seed": list(op.output("Seed")),
        "Mean": list(op.output("Mean")),
        "Variance": list(op.output("Variance")),
        "GRAD@Out": [_grad_var_name(op.output("Out")[0])],
    }
    outputs = {}
    for slot in ("X", "Y", "Scale"):
        n = op.input(slot)[0]
        if n not in no_grad_set:
            outputs["X@" + slot] = [_grad_var_name(n)]
    n = op.input("Bias")[0]
    if n not in no_grad_set:
        outputs["X@Bias"] = [_grad_var_name(n)]
    if not outputs:
        return []
    return [GradOpDesc("fused_dropout_add_ln_grad", inputs, outputs,
                       dict(op.attrs))]


@register_op(
    "fused_dropout_add_ln",
    inputs=("X", "Y", "Scale", "Bias"),
    outputs=("Out", "R", "Mean", "Variance", "Seed"),
    attrs={"dropout_prob": 0.0, "is_test": False, "epsilon": 1e-5,
           "begin_norm_axis": 1, "fix_seed": False, "seed": 0},
    grad_maker=_fused_dropout_add_ln_grad_maker,
    n_rng=1,
)
def fused_dropout_add_ln_op(ctx, x, y, scale, bias, dropout_prob=0.0,
                            is_test=False, epsilon=1e-5, begin_norm_axis=1,
                            fix_seed=False, seed=0, **_):
    """Out = LayerNorm(X + dropout_upscale(Y)): the transformer-encoder
    epilogue as ONE op, lowered to a single-HBM-pass Pallas kernel on TPU
    (pallas_kernels/fused_ln.py; jnp fallback elsewhere).

    TPU-native counterpart of the reference's
    fused_fc_elementwise_layernorm op
    (paddle/fluid/operators/fused/fused_fc_elementwise_layernorm_op.cu —
    inference-only there), extended with in-kernel dropout for training:
    measured 1.82x the composed dropout->add->layer_norm emission fwd+bwd
    at the flagship BERT shape (tools/bench_fused_ln_probe.py).

    The dropout mask is never materialized: the forward draws it from the
    on-core PRNG seeded by the Seed output (two u32 words stored as
    int32), and the grad op re-draws the identical mask from that saved
    seed — the Mask-output contract of the dropout op at 1/12288th the
    memory.  The backward's only large residual is the R output (the
    post-dropout residual sum); X and Y are NOT saved for it (dx == dr,
    dy == mask*dr/q).  Dropout semantics are upscale_in_train with the
    realized keep probability round(q*2^32)/2^32.
    """
    from ..pallas_kernels import fused_ln as _fln

    p = 0.0 if is_test else float(dropout_prob)
    if p > 0.0:
        key = jax.random.key(seed) if fix_seed else ctx.rng()
        seed_arr = jax.random.bits(key, (2,), jnp.uint32)
    else:
        seed_arr = jnp.zeros((2,), jnp.uint32)
    z, r, mean, var = _fln.fused_ln_fwd(x, y, scale, bias, p, seed_arr,
                                        epsilon, begin_norm_axis)
    return z, r, mean, var, seed_arr.astype(jnp.int32)


@register_op(
    "fused_dropout_add_ln_grad",
    inputs=("R", "Scale", "Seed", "Mean", "Variance", "GRAD@Out"),
    outputs=("X@X", "X@Y", "X@Scale", "X@Bias"),
    attrs={"dropout_prob": 0.0, "is_test": False, "epsilon": 1e-5,
           "begin_norm_axis": 1, "fix_seed": False, "seed": 0},
    grad_maker=None,
)
def fused_dropout_add_ln_grad_op(ctx, r, scale, seed_words, mean, var,
                                 dz, dropout_prob=0.0, is_test=False,
                                 epsilon=1e-5, begin_norm_axis=1, **_):
    # NB: the Seed INPUT is named seed_words because the attr dict also
    # carries a (fix_seed-mode) "seed" attr passed as a kwarg
    from ..pallas_kernels import fused_ln as _fln

    p = 0.0 if is_test else float(dropout_prob)
    return _fln.fused_ln_bwd(r, scale, seed_words, mean, var, dz, p,
                             epsilon, begin_norm_axis)


fused_dropout_add_ln_op.opdef.rng_when = _fdaln_uses_dropout


@register_op(
    "ring_attention",
    inputs=("Q", "K", "V"),
    outputs=("Out",),
    attrs={"causal": False, "scale": 0.0, "axis": "sp"},
)
def ring_attention_op(ctx, q, k, v, causal=False, scale=0.0, axis="sp"):
    """Context-parallel attention: when lowered inside a shard_map whose
    mesh has `axis` sharding the SEQUENCE dim, runs the K/V-rotation ring
    (parallel/ring_attention.py); otherwise falls back to dense flash
    attention (single-device semantics are identical).

    NEW capability vs the reference (no CP/SP existed; SURVEY.md §5).
    scale=0.0 means 1/sqrt(head_dim).

    The batch-DP executor shards feeds on dim 0 over ctx.data_axis — that
    axis must NOT trigger the ring (each rank already holds full sequences;
    treating batch shards as sequence chunks would be silently wrong).  The
    ring engages only for a distinct sequence axis, i.e. under a
    seq-sharded shard_map such as parallel.make_ring_attention_sharded.
    """
    sm_scale = scale if scale else None
    if axis in ctx.axis_names and axis != ctx.data_axis:
        from ..parallel import ring_attention as _ring

        return _ring(q, k, v, axis, causal=causal, sm_scale=sm_scale)
    from ..pallas_kernels import flash_attention as _fa

    return _fa(q, k, v, causal=causal, sm_scale=sm_scale)


@register_op(
    "sync_batch_norm",
    inputs=("X", "Scale", "Bias", "Mean", "Variance"),
    outputs=("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance",
             "ReserveSpace"),
    attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
           "data_layout": "NCHW", "use_global_stats": False},
    grad_maker="auto",
    no_grad_inputs=("Mean", "Variance"),
)
def sync_batch_norm(ctx, x, scale, bias, mean, variance, momentum=0.9,
                    epsilon=1e-5, is_test=False, data_layout="NCHW",
                    use_global_stats=False, **_):
    """Cross-replica batch norm (sync_batch_norm_op.cu): statistics are
    reduced over the data-parallel mesh axis with lax.pmean — the TPU
    replacement for the reference's in-kernel ncclAllReduce.  Outside a
    shard_map (single device) it degenerates to plain batch_norm."""
    nchw = data_layout in ("NCHW", "AnyLayout")
    c_ax = 1 if nchw else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_ax)
    cshape = [1] * x.ndim
    cshape[c_ax] = x.shape[c_ax]

    axis_name = ctx.axis_names[0] if (ctx is not None and ctx.axis_names) \
        else None
    return _bn_impl(x, scale, bias, mean, variance, axes, cshape, momentum,
                    epsilon, is_test or use_global_stats,
                    axis_name=axis_name)


