"""Additional loss / metric ops.

Parity (paddle/fluid/operators/): bpr_loss_op.cc, rank_loss_op.cc,
margin_rank_loss_op.cc, sigmoid_focal_loss_op.cc,
teacher_student_sigmoid_loss_op.cc, mean_iou_op.cc, center_loss_op.cc,
warpctc_op.cc (CTC forward via lax.scan instead of the vendored warp-ctc
CUDA lib), edit_distance_op.cc.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_NEG_INF = -1e30


@register_op("bpr_loss", inputs=("X", "Label"), outputs=("Y",),
             no_grad_inputs=("Label",))
def bpr_loss(ctx, x, label):
    """Bayesian personalized ranking loss (bpr_loss_op.cc): for each row,
    -mean_j log(sigmoid(x[label] - x[j])) over j != label."""
    n, c = x.shape
    lbl = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lbl[:, None], axis=1)
    diff = pos - x
    logsig = jax.nn.log_sigmoid(diff)
    mask = jnp.ones((n, c), bool).at[jnp.arange(n), lbl].set(False)
    loss = -jnp.sum(jnp.where(mask, logsig, 0.0), axis=1) / (c - 1)
    return loss[:, None]


@register_op("rank_loss", inputs=("Label", "Left", "Right"),
             outputs=("Out",), no_grad_inputs=("Label",))
def rank_loss(ctx, label, left, right):
    """RankNet pairwise loss (rank_loss_op.cc)."""
    d = left - right
    return d * (1 - label) + jnp.log1p(jnp.exp(-jnp.abs(d))) + jnp.maximum(
        -d, 0.0)


@register_op("margin_rank_loss", inputs=("Label", "X1", "X2"),
             outputs=("Out", "Activated"), attrs={"margin": 0.0},
             no_grad_inputs=("Label",))
def margin_rank_loss(ctx, label, x1, x2, margin=0.0):
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return out, (out > 0).astype(x1.dtype)


@register_op("sigmoid_focal_loss", inputs=("X", "Label", "FgNum"),
             outputs=("Out",), attrs={"gamma": 2.0, "alpha": 0.25},
             no_grad_inputs=("Label", "FgNum"))
def sigmoid_focal_loss(ctx, x, label, fg_num, gamma=2.0, alpha=0.25):
    """Focal loss (sigmoid_focal_loss_op.cc): x [N, C] logits, label [N, 1]
    in [0, C] with 0 = background (class c is column c-1)."""
    n, c = x.shape
    lbl = label.reshape(-1).astype(jnp.int32)
    target = (lbl[:, None] == jnp.arange(1, c + 1)[None, :]).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.logaddexp(0.0, jnp.where(target == 1, -x, x))
    p_t = jnp.where(target == 1, p, 1 - p)
    a_t = jnp.where(target == 1, alpha, 1 - alpha)
    fg = jnp.maximum(fg_num.reshape(()).astype(x.dtype), 1.0)
    return a_t * jnp.power(1 - p_t, gamma) * ce / fg


@register_op("teacher_student_sigmoid_loss", inputs=("X", "Label"),
             outputs=("Y",), attrs={"soft_max_up_bound": 15.0,
                                    "soft_max_lower_bound": -15.0},
             no_grad_inputs=("Label",))
def teacher_student_sigmoid_loss(ctx, x, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """CTR distillation loss (teacher_student_sigmoid_loss_op.cc): label<0
    is teacher score -(label+1); else binary click label."""
    x = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    lbl = label.reshape(x.shape)
    ce = jnp.logaddexp(0.0, x) - x * (lbl > 0).astype(x.dtype)
    teacher = -(lbl + 1)
    tce = jnp.logaddexp(0.0, x) - x * teacher
    return jnp.where(lbl < 0, tce, ce)


@register_op("mean_iou", inputs=("Predictions", "Labels"),
             outputs=("OutMeanIou", "OutWrong", "OutCorrect"),
             attrs={"num_classes": 2}, grad_maker=None)
def mean_iou(ctx, pred, labels, num_classes=2):
    """Mean intersection-over-union over classes (mean_iou_op.cc)."""
    p = pred.reshape(-1).astype(jnp.int32)
    l = labels.reshape(-1).astype(jnp.int32)
    valid = (l >= 0) & (l < num_classes)
    cid = jnp.arange(num_classes)
    inter = jnp.sum((p[:, None] == cid) & (l[:, None] == cid)
                    & valid[:, None], axis=0).astype(jnp.float32)
    pred_cnt = jnp.sum((p[:, None] == cid) & valid[:, None],
                       axis=0).astype(jnp.float32)
    lbl_cnt = jnp.sum((l[:, None] == cid) & valid[:, None],
                      axis=0).astype(jnp.float32)
    union = pred_cnt + lbl_cnt - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    present = (union > 0).sum().astype(jnp.float32)
    miou = jnp.sum(iou) / jnp.maximum(present, 1.0)
    wrong = (lbl_cnt - inter).astype(jnp.int32)
    correct = inter.astype(jnp.int32)
    return miou, wrong, correct


@register_op("center_loss", inputs=("X", "Label", "Centers", "CenterUpdateRate"),
             outputs=("CentersOut", "SampleCenterDiff", "Loss"),
             attrs={"cluster_num": 2, "need_update": True},
             no_grad_inputs=("Label", "Centers", "CenterUpdateRate"))
def center_loss(ctx, x, label, centers, update_rate, cluster_num=2,
                need_update=True):
    """Center loss (center_loss_op.cc): pulls features toward per-class
    centers; centers update by averaged in-batch diffs."""
    lbl = label.reshape(-1).astype(jnp.int32)
    cx = centers[lbl]                      # [N, D]
    diff = x - cx
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if need_update:
        rate = update_rate.reshape(())
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[lbl].add(1.0)
        sums = jnp.zeros_like(centers).at[lbl].add(diff)
        centers_new = centers + rate * sums / (counts[:, None] + 1.0)
    else:
        centers_new = centers
    return centers_new, diff, loss


@register_op("warpctc", inputs=("Logits", "Label"),
             outputs=("WarpCTCGrad", "Loss"),
             attrs={"blank": 0, "norm_by_times": False},
             no_grad_inputs=("Label",),
             grad_maker="auto")
def warpctc(ctx, logits, label, blank=0, norm_by_times=False):
    """CTC loss (warpctc_op.cc) on dense inputs: logits [B, T, C] (padded),
    label [B, L] padded with -1.  Forward-algorithm in log space via
    lax.scan — the TPU-native replacement for the vendored warp-ctc CUDA
    library.  Returns (grad placeholder, loss [B, 1]); gradients flow via
    the auto vjp of this forward."""
    if logits.ndim == 2:
        logits = logits[None]
    B, T, C = logits.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # extended label: blank, l1, blank, l2, ... blank (length 2L+1)
    lbl = label.astype(jnp.int32)
    ext = jnp.full((B, 2 * L + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(lbl >= 0, lbl, blank))
    valid_ext = jnp.ones((B, 2 * L + 1), bool)
    valid_ext = valid_ext.at[:, 1::2].set(lbl >= 0)
    # label length per batch
    lab_len = jnp.sum(lbl >= 0, axis=1)
    ext_len = 2 * lab_len + 1

    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-2)[:, :-2]
    can_skip = (ext != blank) & (ext != ext_m2)

    a0 = jnp.full((B, 2 * L + 1), _NEG_INF)
    a0 = a0.at[:, 0].set(logp[:, 0, blank])
    first_lbl = jnp.take_along_axis(
        logp[:, 0, :], jnp.clip(ext[:, 1:2], 0, C - 1), axis=1)[:, 0]
    a0 = a0.at[:, 1].set(jnp.where(lab_len > 0, first_lbl, _NEG_INF))

    def step(alpha, t):
        lp = jnp.take_along_axis(logp[:, t, :], jnp.clip(ext, 0, C - 1),
                                 axis=1)
        am1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                      constant_values=_NEG_INF)[:, :-1]
        am2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                      constant_values=_NEG_INF)[:, :-2]
        am2 = jnp.where(can_skip, am2, _NEG_INF)
        new = jnp.logaddexp(jnp.logaddexp(alpha, am1), am2) + lp
        return new, None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, T))
    idx_last = jnp.maximum(ext_len - 1, 0)
    idx_prev = jnp.maximum(ext_len - 2, 0)
    last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(last, prev)
    if norm_by_times:
        loss = loss / T
    return jnp.zeros_like(logits), loss[:, None]


@register_op("edit_distance", inputs=("Hyps", "Refs"),
             outputs=("Out", "SequenceNum"),
             attrs={"normalized": False}, grad_maker=None)
def edit_distance(ctx, hyps, refs, normalized=False):
    """Levenshtein distance per pair (edit_distance_op.cc) on dense int
    sequences padded with -1."""
    B, Lh = hyps.shape
    Lr = refs.shape[1]
    h = hyps.astype(jnp.int32)
    r = refs.astype(jnp.int32)
    hlen = jnp.sum(h >= 0, axis=1)
    rlen = jnp.sum(r >= 0, axis=1)

    def one(hrow, rrow, hl, rl):
        row0 = jnp.arange(Lr + 1, dtype=jnp.float32)

        def outer(i, row):
            def inner(j, cur):
                cost = jnp.where(hrow[i - 1] == rrow[j - 1], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(cur[j - 1] + 1, row[j] + 1),
                                  row[j - 1] + cost)
                return cur.at[j].set(val)

            cur = jnp.full((Lr + 1,), 0.0).at[0].set(i * 1.0)
            cur = lax.fori_loop(1, Lr + 1, inner, cur)
            return cur

        def body(i, row):
            return jnp.where(i <= hl, outer(i, row), row)

        final = lax.fori_loop(1, Lh + 1, body, row0)
        d = final[rl]
        return jnp.where(rl == 0, hl.astype(jnp.float32), d)

    d = jax.vmap(one)(h, r, hlen, rlen)
    if normalized:
        d = d / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return d[:, None], jnp.asarray(B, jnp.int64)
