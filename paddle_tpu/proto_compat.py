"""Reference model-format compatibility: the ``__model__`` ProgramDesc
protobuf and per-variable LoDTensor binary streams.

The reference serializes models as a proto2 ``ProgramDesc``
(paddle/fluid/framework/framework.proto:212 — blocks:1, version:4) and each
parameter as a binary stream (lod_tensor.cc:219 SerializeToStream: uint32
version, LoD levels, then tensor_util.cc:383 TensorToStream: uint32 version,
int32 desc-size + VarType.TensorDesc proto, raw data).  This module reads
AND writes both formats with a minimal hand-rolled proto2 wire codec (no
generated code, no protobuf dependency), so

* ``load_inference_model`` accepts a directory saved by the reference
  (completing the "swap CUDAPlace for TPUPlace, keep everything" story for
  saved models, not just code), and
* ``save_inference_model(..., legacy_format=True)`` emits a directory the
  reference can load.

Field numbers below cite framework.proto lines.
"""

import struct

import numpy as np

__all__ = [
    "parse_program_desc",
    "serialize_program_desc",
    "read_lod_tensor",
    "write_lod_tensor",
    "is_program_desc",
]

# -- proto2 wire format ------------------------------------------------------

_WT_VARINT = 0
_WT_64BIT = 1
_WT_LEN = 2
_WT_32BIT = 5


def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _parse_fields(buf):
    """Decode one message into {field_number: [raw values]} (repeated fields
    accumulate in order)."""
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fno, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            val, pos = _read_varint(buf, pos)
        elif wt == _WT_64BIT:
            val = struct.unpack_from("<q", buf, pos)[0]
            pos += 8
        elif wt == _WT_LEN:
            ln, pos = _read_varint(buf, pos)
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wt == _WT_32BIT:
            val = struct.unpack_from("<i", buf, pos)[0]
            pos += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        fields.setdefault(fno, []).append(val)
    return fields


def _first(fields, fno, default=None):
    v = fields.get(fno)
    return v[0] if v else default


def _signed64(v):
    """proto int32/int64 varints are two's-complement 64-bit."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _unpack_repeated_varints(fields, fno):
    """repeated int (possibly packed): packed entries arrive as one LEN
    payload, unpacked as individual varints."""
    out = []
    for v in fields.get(fno, []):
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed64(x))
        else:
            out.append(_signed64(v))
    return out


def _unpack_repeated_floats(fields, fno):
    out = []
    for v in fields.get(fno, []):
        if isinstance(v, bytes):
            out.extend(struct.unpack("<%df" % (len(v) // 4), v))
        else:
            out.append(struct.unpack("<f", struct.pack("<i", v))[0])
    return out


class _Writer:
    def __init__(self):
        self.parts = []

    def varint(self, fno, val):
        self._key(fno, _WT_VARINT)
        self._varint(val if val >= 0 else val + (1 << 64))
        return self

    def _key(self, fno, wt):
        self._varint((fno << 3) | wt)

    def _varint(self, v):
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self.parts.append(bytes(out))

    def bytes_field(self, fno, payload):
        self._key(fno, _WT_LEN)
        self._varint(len(payload))
        self.parts.append(payload)
        return self

    def string(self, fno, s):
        return self.bytes_field(fno, s.encode("utf-8"))

    def float32(self, fno, f):
        self._key(fno, _WT_32BIT)
        self.parts.append(struct.pack("<f", f))
        return self

    def getvalue(self):
        return b"".join(self.parts)


# -- enums (framework.proto) -------------------------------------------------

# VarType.Type (framework.proto:105): pod dtypes + container kinds
_DTYPE_FROM_PB = {
    0: "bool", 1: "int16", 2: "int32", 3: "int64",
    4: "float16", 5: "float32", 6: "float64", 20: "uint8", 21: "int8",
}
_DTYPE_TO_PB = {v: k for k, v in _DTYPE_FROM_PB.items()}

_PB_LOD_TENSOR = 7
_PB_SELECTED_ROWS = 8
_PB_FEED_MINIBATCH = 9
_PB_FETCH_LIST = 10
_PB_STEP_SCOPES = 11
_PB_LOD_TENSOR_ARRAY = 13
_PB_READER = 15
_PB_RAW = 17

_VARTYPE_FROM_PB = {
    _PB_LOD_TENSOR: "lod_tensor",
    _PB_SELECTED_ROWS: "selected_rows",
    _PB_LOD_TENSOR_ARRAY: "lod_tensor_array",
    _PB_READER: "reader",
    _PB_STEP_SCOPES: "step_scopes",
    _PB_RAW: "raw",
    _PB_FEED_MINIBATCH: "lod_tensor",
    _PB_FETCH_LIST: "lod_tensor",
}

# AttrType (framework.proto:26) -> OpDesc.Attr value field number
_ATTR_INT, _ATTR_FLOAT, _ATTR_STRING = 0, 1, 2
_ATTR_INTS, _ATTR_FLOATS, _ATTR_STRINGS = 3, 4, 5
_ATTR_BOOLEAN, _ATTR_BOOLEANS, _ATTR_BLOCK = 6, 7, 8
_ATTR_LONG, _ATTR_BLOCKS, _ATTR_LONGS = 9, 10, 11


# -- ProgramDesc decode ------------------------------------------------------


def is_program_desc(data):
    """Cheap sniff: our JSON IR starts with '{'; a ProgramDesc starts with a
    field-1 LEN key (0x0a) for blocks."""
    return bool(data) and data[:1] == b"\x0a"


def _parse_attr(buf):
    """OpDesc.Attr (framework.proto:44): name=1, type=2, i=3, f=4, s=5,
    ints=6, floats=7, strings=8, b=10, bools=11, block_idx=12, l=13,
    blocks_idx=14, longs=15."""
    f = _parse_fields(buf)
    name = _first(f, 1, b"").decode("utf-8")
    atype = _first(f, 2, 0)
    if atype == _ATTR_INT:
        val = _signed64(_first(f, 3, 0)) & 0xFFFFFFFF
        val = val - (1 << 32) if val >= (1 << 31) else val
    elif atype == _ATTR_FLOAT:
        raw = _first(f, 4, 0)
        val = struct.unpack("<f", struct.pack("<I", raw & 0xFFFFFFFF))[0] \
            if not isinstance(raw, float) else raw
    elif atype == _ATTR_STRING:
        val = _first(f, 5, b"").decode("utf-8")
    elif atype == _ATTR_INTS:
        val = [v - (1 << 32) if v >= (1 << 31) else v
               for v in (x & 0xFFFFFFFF for x in
                         _unpack_repeated_varints(f, 6))]
    elif atype == _ATTR_FLOATS:
        val = _unpack_repeated_floats(f, 7)
    elif atype == _ATTR_STRINGS:
        val = [s.decode("utf-8") for s in f.get(8, [])]
    elif atype == _ATTR_BOOLEAN:
        val = bool(_first(f, 10, 0))
    elif atype == _ATTR_BOOLEANS:
        val = [bool(v) for v in _unpack_repeated_varints(f, 11)]
    elif atype == _ATTR_BLOCK:
        val = _first(f, 12, 0)
    elif atype == _ATTR_LONG:
        val = _signed64(_first(f, 13, 0))
    elif atype == _ATTR_BLOCKS:
        val = _unpack_repeated_varints(f, 14)
    elif atype == _ATTR_LONGS:
        val = _unpack_repeated_varints(f, 15)
    else:
        val = None
    return name, val


def _parse_op_var(buf):
    """OpDesc.Var (framework.proto:62): parameter=1, arguments=2."""
    f = _parse_fields(buf)
    slot = _first(f, 1, b"").decode("utf-8")
    args = [a.decode("utf-8") for a in f.get(2, [])]
    return slot, args


def _parse_tensor_desc(buf):
    """VarType.TensorDesc (framework.proto:139): data_type=1, dims=2."""
    f = _parse_fields(buf)
    enum = _first(f, 1, 5)
    if enum not in _DTYPE_FROM_PB:
        raise ValueError(
            "unsupported VarType.Type enum %r in TensorDesc (pod dtypes "
            "only; framework.proto:105)" % (enum,))
    dims = _unpack_repeated_varints(f, 2)
    return _DTYPE_FROM_PB[enum], dims


def _parse_var_type(buf):
    """VarType (framework.proto:105): type=1, lod_tensor=3 (LoDTensorDesc:
    tensor=1, lod_level=2), tensor_array=4."""
    f = _parse_fields(buf)
    kind = _first(f, 1, _PB_LOD_TENSOR)
    dtype, dims, lod_level = None, None, 0
    sub = _first(f, 3) or _first(f, 4)
    if sub is not None:
        sf = _parse_fields(sub)
        td = _first(sf, 1)
        if td is not None:
            dtype, dims = _parse_tensor_desc(td)
        lod_level = _first(sf, 2, 0)
    return _VARTYPE_FROM_PB.get(kind, "lod_tensor"), dtype, dims, lod_level


def _parse_var_desc(buf):
    """VarDesc (framework.proto:166): name=1, type=2, persistable=3,
    need_check_feed=4."""
    f = _parse_fields(buf)
    name = _first(f, 1, b"").decode("utf-8")
    vtype, dtype, dims, lod_level = _parse_var_type(_first(f, 2, b""))
    return {
        "name": name,
        "shape": list(dims) if dims is not None else None,
        "dtype": dtype,
        "lod_level": lod_level,
        "persistable": bool(_first(f, 3, 0)),
        "stop_gradient": False,
        "type": vtype,
        "is_data": bool(_first(f, 4, 0)),
        "is_parameter": False,
    }


def _parse_op_desc(buf):
    """OpDesc (framework.proto:42): inputs=1, outputs=2, type=3, attrs=4."""
    f = _parse_fields(buf)
    inputs = dict(_parse_op_var(v) for v in f.get(1, []))
    outputs = dict(_parse_op_var(v) for v in f.get(2, []))
    attrs = dict(_parse_attr(a) for a in f.get(4, []))
    return {
        "type": _first(f, 3, b"").decode("utf-8"),
        "inputs": inputs,
        "outputs": outputs,
        "attrs": attrs,
    }


def _parse_block_desc(buf):
    """BlockDesc (framework.proto:175): idx=1, parent_idx=2, vars=3, ops=4."""
    f = _parse_fields(buf)
    parent = _signed64(_first(f, 2, 0)) & 0xFFFFFFFF
    if parent >= (1 << 31):
        parent -= 1 << 32
    return {
        "idx": _first(f, 1, 0),
        "parent_idx": parent,
        "vars": [_parse_var_desc(v) for v in f.get(3, [])],
        "ops": [_parse_op_desc(o) for o in f.get(4, [])],
    }


def parse_program_desc(data):
    """ProgramDesc bytes -> the JSON-IR dict Program.from_dict accepts
    (framework.proto:212: blocks=1, version=4)."""
    f = _parse_fields(data)
    blocks = [_parse_block_desc(b) for b in f.get(1, [])]
    for b in blocks:
        # reference marks parameters only via persistable + initializer
        # convention; mark persistable non-data lod_tensor vars consumed by
        # no producer op as parameters so optimizers/io see them
        produced = {n for op in b["ops"] for ns in op["outputs"].values()
                    for n in ns}
        for v in b["vars"]:
            if (v["persistable"] and v["type"] == "lod_tensor"
                    and v["name"] not in produced
                    and v["name"] not in ("feed", "fetch")):
                v["is_parameter"] = True
    return {"version": 1, "random_seed": 0, "blocks": blocks}


# -- ProgramDesc encode ------------------------------------------------------


# attrs that are block references in the reference schema (framework.proto
# AttrType BLOCK/BLOCKS; e.g. conditional_block/while's sub_block) — they
# ride as plain ints in our IR, so the emitter keys on the attr name
_BLOCK_ATTRS = {"sub_block", "block"}
_BLOCKS_ATTRS = {"sub_blocks", "blocks"}


# Attr names whose values are string/float lists in the reference op
# definitions; an empty value must still round-trip with the right AttrType
# (op_proto_maker.h op_role_var/op_callstack are STRINGS; the detection-op
# geometry attrs are FLOATS).
_EMPTY_STRINGS_ATTRS = frozenset({
    "op_role_var", "op_callstack", "readers", "grad_var_names",
    "original_var_names", "table_names", "epmap", "endpoints",
    "feed_var_names", "fetch_var_names", "input_names", "output_names",
})
_EMPTY_FLOATS_ATTRS = frozenset({
    "min_sizes", "max_sizes", "aspect_ratios", "variances", "anchor_sizes",
    "stride", "densities", "fixed_sizes", "fixed_ratios", "scales",
    "expand_ratios", "steps",
})


def _emit_attr(name, val):
    w = _Writer()
    w.string(1, name)
    if name in _BLOCK_ATTRS and isinstance(val, int):
        w.varint(2, _ATTR_BLOCK).varint(12, val)
    elif name in _BLOCKS_ATTRS and isinstance(val, (list, tuple)) \
            and all(isinstance(v, int) for v in val):
        w.varint(2, _ATTR_BLOCKS)
        for v in val:
            w.varint(14, v)
    elif isinstance(val, bool):
        w.varint(2, _ATTR_BOOLEAN).varint(10, int(val))
    elif isinstance(val, int):
        if -(1 << 31) <= val < (1 << 31):
            w.varint(2, _ATTR_INT).varint(3, val & 0xFFFFFFFF)
        else:
            w.varint(2, _ATTR_LONG).varint(13, val)
    elif isinstance(val, float):
        w.varint(2, _ATTR_FLOAT).float32(4, val)
    elif isinstance(val, str):
        w.varint(2, _ATTR_STRING).string(5, val)
    elif isinstance(val, (list, tuple)):
        if not val:
            # the element type is unknowable from an empty value; the
            # reference's typed attr access (boost::get) throws on a type
            # mismatch, so consult a hint table for the known float-list /
            # string-list attr names before defaulting to INTS (the
            # overwhelmingly common case: shape/axis/sections defaults).
            if name in _EMPTY_STRINGS_ATTRS:
                w.varint(2, _ATTR_STRINGS)
            elif name in _EMPTY_FLOATS_ATTRS:
                w.varint(2, _ATTR_FLOATS)
            else:
                w.varint(2, _ATTR_INTS)
        elif all(isinstance(v, bool) for v in val):
            w.varint(2, _ATTR_BOOLEANS)
            for v in val:
                w.varint(11, int(v))
        elif all(isinstance(v, int) for v in val):
            if all(-(1 << 31) <= v < (1 << 31) for v in val):
                w.varint(2, _ATTR_INTS)
                for v in val:
                    w.varint(6, v & 0xFFFFFFFF)
            else:
                w.varint(2, _ATTR_LONGS)
                for v in val:
                    w.varint(15, v)
        elif all(isinstance(v, float) for v in val):
            w.varint(2, _ATTR_FLOATS)
            for v in val:
                w.float32(7, v)
        elif all(isinstance(v, str) for v in val):
            w.varint(2, _ATTR_STRINGS)
            for v in val:
                w.string(8, v)
        else:
            return None  # mixed list: not representable
    else:
        return None  # dicts etc.: framework-internal, skip
    return w.getvalue()


def _emit_tensor_desc(dtype, dims):
    dtype = dtype or "float32"
    if dtype not in _DTYPE_TO_PB:
        raise ValueError(
            "dtype %r has no reference VarType.Type (framework.proto:105 "
            "predates bf16); cast the variable before legacy-format save"
            % (dtype,))
    w = _Writer()
    w.varint(1, _DTYPE_TO_PB[dtype])
    for d in dims or ():
        w.varint(2, d if d is not None else -1)
    return w.getvalue()


_VARTYPE_TO_PB = {
    "lod_tensor": _PB_LOD_TENSOR,
    "selected_rows": _PB_SELECTED_ROWS,
    "lod_tensor_array": _PB_LOD_TENSOR_ARRAY,
    "reader": _PB_READER,
    "step_scopes": _PB_STEP_SCOPES,
    "raw": _PB_RAW,
    # feed/fetch holder vars: the reference executor enforces these exact
    # types on the holders (executor.cc:240,:284), so exported legacy models
    # must carry them or the reference refuses to run the model.
    "feed_minibatch": _PB_FEED_MINIBATCH,
    "fetch_list": _PB_FETCH_LIST,
}


def _emit_var_desc(vd):
    kind = _VARTYPE_TO_PB.get(vd.get("type", "lod_tensor"), _PB_LOD_TENSOR)
    t = _Writer()
    t.varint(1, kind)
    if kind in (_PB_LOD_TENSOR, _PB_SELECTED_ROWS, _PB_LOD_TENSOR_ARRAY) \
            and vd.get("dtype") is not None:
        ltd = _Writer()
        ltd.bytes_field(1, _emit_tensor_desc(vd["dtype"], vd.get("shape")))
        if vd.get("lod_level"):
            ltd.varint(2, vd["lod_level"])
        fno = {_PB_LOD_TENSOR: 3, _PB_SELECTED_ROWS: 2,
               _PB_LOD_TENSOR_ARRAY: 4}[kind]
        if kind == _PB_SELECTED_ROWS:
            t.bytes_field(2, _emit_tensor_desc(vd["dtype"], vd.get("shape")))
        else:
            t.bytes_field(fno, ltd.getvalue())
    w = _Writer()
    w.string(1, vd["name"])
    w.bytes_field(2, t.getvalue())
    if vd.get("persistable"):
        w.varint(3, 1)
    if vd.get("is_data"):
        w.varint(4, 1)
    return w.getvalue()


def _emit_op_desc(od):
    w = _Writer()
    for fno, slots in ((1, od["inputs"]), (2, od["outputs"])):
        for slot, args in sorted(slots.items()):
            v = _Writer()
            v.string(1, slot)
            for a in args:
                v.string(2, a)
            w.bytes_field(fno, v.getvalue())
    w.string(3, od["type"])
    for name, val in sorted(od["attrs"].items()):
        enc = _emit_attr(name, val)
        if enc is not None:
            w.bytes_field(4, enc)
    return w.getvalue()


def serialize_program_desc(prog_dict):
    """JSON-IR dict -> ProgramDesc bytes the reference can parse."""
    w = _Writer()
    for bd in prog_dict["blocks"]:
        b = _Writer()
        b.varint(1, bd["idx"])
        b.varint(2, bd["parent_idx"] or 0)
        for vd in bd["vars"]:
            b.bytes_field(3, _emit_var_desc(vd))
        for od in bd["ops"]:
            b.bytes_field(4, _emit_op_desc(od))
        w.bytes_field(1, b.getvalue())
    ver = _Writer()
    ver.varint(1, 0)
    w.bytes_field(4, ver.getvalue())
    return w.getvalue()


# -- LoDTensor binary streams ------------------------------------------------


def read_lod_tensor(f):
    """One SerializeToStream record (lod_tensor.cc:219) -> (ndarray, lod)."""
    version = struct.unpack("<I", f.read(4))[0]
    if version != 0:
        raise ValueError("unsupported LoDTensor version %d" % version)
    lod_level = struct.unpack("<Q", f.read(8))[0]
    lod = []
    for _ in range(lod_level):
        nbytes = struct.unpack("<Q", f.read(8))[0]
        lod.append(list(struct.unpack("<%dQ" % (nbytes // 8),
                                      f.read(nbytes))))
    tversion = struct.unpack("<I", f.read(4))[0]
    if tversion != 0:
        raise ValueError("unsupported Tensor version %d" % tversion)
    desc_size = struct.unpack("<i", f.read(4))[0]
    dtype_name, dims = _parse_tensor_desc(f.read(desc_size))
    npdtype = np.dtype(dtype_name)
    count = int(np.prod(dims)) if dims else 1
    data = np.frombuffer(f.read(count * np.dtype(npdtype).itemsize),
                         dtype=npdtype)
    return data.reshape(dims), lod


def write_lod_tensor(f, arr, lod=()):
    """ndarray -> one SerializeToStream record."""
    arr = np.ascontiguousarray(arr)
    f.write(struct.pack("<I", 0))
    f.write(struct.pack("<Q", len(lod)))
    for level in lod:
        f.write(struct.pack("<Q", len(level) * 8))
        f.write(struct.pack("<%dQ" % len(level), *level))
    f.write(struct.pack("<I", 0))
    desc = _emit_tensor_desc(arr.dtype.name, list(arr.shape))
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(arr.tobytes())
