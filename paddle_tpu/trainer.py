"""Dataset-driven training loop (the C++ trainer/device-worker path).

Parity: paddle/fluid/framework/trainer.h (MultiTrainer),
hogwild_worker.cc:163 (TrainFiles: ``while reader->Next(): run ops``) and
Executor::RunFromDataset (executor.cc:182), entered from Python via
``Executor.train_from_dataset`` (executor.py:1098).

TPU-native shape: the reference runs N CPU worker threads each interpreting
the op list over its own data feed.  On TPU there is one compiled program
and one device stream, so the N "device workers" become N *feed* workers
that parse/batch in parallel (native C++ store + blocking queue) while a
single dispatcher drives the compiled XLA step — same epoch/metric
semantics, hardware-appropriate execution.
"""

import threading
import time

import numpy as np

__all__ = ["train_from_dataset", "infer_from_dataset", "TrainerDesc",
           "DeviceWorker", "Hogwild", "MultiTrainer"]


class TrainerDesc:
    """Facade mirroring trainer_desc.py (proto emission is replaced by a
    plain config object — there is no C++ proto consumer here)."""

    def __init__(self):
        self._worker = "HogwildWorker"
        self._thread_num = 1
        self._fetch_vars = []
        self._fetch_info = []
        self._print_period = 100

    def set_thread(self, n):
        self._thread_num = n

    def set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self._fetch_vars = fetch_vars
        self._fetch_info = fetch_info
        self._print_period = print_period


class DeviceWorker:
    pass


class Hogwild(DeviceWorker):
    pass


class MultiTrainer:
    pass


def _run_loop(exe, program, dataset, scope, thread, fetch_list, fetch_info,
              print_period, train, checkpoint_manager=None):
    """checkpoint_manager: an io.CheckpointManager; every step the loop
    offers it a crash-safe save (maybe_save fires on its save_interval).
    Restoring is the CALLER's move — run the startup program, then
    CheckpointManager.restore(), then enter this loop — because only the
    caller knows whether a fresh scope or a supervised relaunch is in
    play."""
    from .core.executor import global_scope
    from .native.queue import NativeBlockingQueue, QueueClosed

    if dataset is None:
        raise ValueError("dataset must be provided")
    scope = scope or global_scope()
    fetch_list = fetch_list or []
    fetch_info = fetch_info or [getattr(v, "name", str(v)) for v in fetch_list]
    nthread = max(int(thread) or dataset._thread or 1, 1)

    # one feed producer decouples native parse/pad from the device step; the
    # reference's N device workers have no analog on a single-stream TPU
    # (`thread` still sizes the prefetch window)
    queue = NativeBlockingQueue(capacity=max(4 * nthread, 8))
    names = [v.name for v in dataset._use_vars]

    def feed_worker():
        try:
            for feed in dataset._iter_batches(drop_last=train):
                try:
                    queue.push([feed[n] for n in names])
                except QueueClosed:
                    return
        finally:
            queue.close()

    workers = [threading.Thread(target=feed_worker, daemon=True)]
    for w in workers:
        w.start()

    step = 0
    t0 = time.time()
    results = []
    try:
        while True:
            try:
                arrs = queue.pop()
            except QueueClosed:
                break
            feed = dict(zip(names, arrs))
            out = exe.run(program, feed=feed, fetch_list=fetch_list,
                          scope=scope)
            step += 1
            if fetch_list and print_period and step % print_period == 0:
                vals = ", ".join(
                    "%s=%s" % (info, np.asarray(v).reshape(-1)[:1])
                    for info, v in zip(fetch_info, out))
                print("[trainer] step %d (%.1f steps/s): %s"
                      % (step, step / max(time.time() - t0, 1e-9), vals))
            if fetch_list:
                results = out
            if train and checkpoint_manager is not None:
                checkpoint_manager.maybe_save(exe, program, step)
    finally:
        queue.kill()
        for w in workers:
            w.join(timeout=5)
    return results


def _pipeline_train(exe, program, dataset, scope, fetch_list, fetch_info,
                    print_period):
    """Host-queue pipeline scheduler (reference PipelineTrainer +
    SectionWorker, framework/pipeline_trainer.cc:24, section_worker.cc:141):
    one worker thread per section, microbatch feed dicts flowing through
    native blocking queues, sections running on their own places against
    the SHARED scope (per-microbatch param updates, the reference's async
    section semantics)."""
    from .core.executor import Executor, global_scope
    from .native.queue import NativeBlockingQueue, QueueClosed

    popt = program._pipeline_opt
    sections = popt["sections"]
    scope = scope or global_scope()
    fetch_list = fetch_list or []
    fetch_info = fetch_info or [getattr(v, "name", str(v)) for v in fetch_list]
    qsize = max(int(popt.get("queue_size", 30)), 2)
    queues = [NativeBlockingQueue(capacity=qsize) for _ in sections]

    results = []
    stats = {"step": 0, "t0": time.time()}
    errors = []

    def abort():
        # unblock every producer AND consumer so join() can't deadlock on a
        # failed stage (push/pop block indefinitely otherwise)
        for q in queues:
            q.kill()

    def feeder():
        names = sections[0]["in_names"]
        try:
            for feed in dataset._iter_batches(drop_last=True):
                try:
                    queues[0].push([feed[n] for n in names])
                except QueueClosed:
                    return
        except Exception as e:
            errors.append(e)
            abort()
        finally:
            queues[0].close()

    def section_worker(i):
        sec = sections[i]
        place = sec["place"]
        sec_exe = Executor(place) if place is not None else exe
        in_names, out_names = sec["in_names"], sec["out_names"]
        last = i == len(sections) - 1
        # names this section itself (re)produces must be fetched, never
        # forwarded from the incoming feed (stale pre-section values)
        produced_here = set(
            n for op in sec["program"].global_block().ops
            for n in op.output_arg_names if n)
        try:
            while True:
                try:
                    arrs = queues[i].pop()
                except QueueClosed:
                    break
                feed = dict(zip(in_names, arrs))
                fetches = fetch_list if last else [
                    n for n in out_names if n in produced_here]
                out = sec_exe.run(sec["program"], feed=feed,
                                  fetch_list=fetches, scope=scope)
                if last:
                    stats["step"] += 1
                    if fetch_list:
                        results[:] = out
                        if print_period and stats["step"] % print_period == 0:
                            vals = ", ".join(
                                "%s=%s" % (info, np.asarray(v).reshape(-1)[:1])
                                for info, v in zip(fetch_info, out))
                            print("[pipeline] step %d (%.1f steps/s): %s" % (
                                stats["step"],
                                stats["step"] / max(time.time() - stats["t0"],
                                                    1e-9), vals))
                else:
                    produced = dict(zip(fetches, out))
                    try:
                        queues[i + 1].push([
                            produced[n] if n in produced else feed[n]
                            for n in out_names])
                    except QueueClosed:
                        break
        except Exception as e:  # propagate worker failures to the driver
            errors.append(e)
            abort()
        finally:
            if not last:
                queues[i + 1].close()

    threads = [threading.Thread(target=feeder, daemon=True)]
    threads += [threading.Thread(target=section_worker, args=(i,), daemon=True)
                for i in range(len(sections))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    abort()
    if errors:
        raise errors[0]
    return results


def train_from_dataset(exe, program, dataset, scope, thread, fetch_list,
                       fetch_info, print_period, checkpoint_manager=None):
    if getattr(program, "_pipeline_opt", None):
        return _pipeline_train(exe, program, dataset, scope, fetch_list,
                               fetch_info, print_period)
    return _run_loop(exe, program, dataset, scope, thread, fetch_list,
                     fetch_info, print_period, train=True,
                     checkpoint_manager=checkpoint_manager)


def infer_from_dataset(exe, program, dataset, scope, thread, fetch_list,
                       fetch_info, print_period):
    return _run_loop(exe, program, dataset, scope, thread, fetch_list,
                     fetch_info, print_period, train=False)
