"""Install sanity check (reference python/paddle/fluid/install_check.py:45
run_check — builds a tiny fc model, runs one train step, prints success)."""

import numpy as np

__all__ = ["run_check"]


def run_check():
    import paddle_tpu as fluid

    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data("install_check_x", shape=[2])
        linear = fluid.layers.fc(x, 2)
        loss = fluid.layers.mean(linear)
        fluid.optimizer.SGD(0.01).minimize(loss)
    feed = {"install_check_x": np.ones((2, 2), "float32")}

    def _try(place):
        exe = fluid.Executor(place)
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            exe.run(prog, feed=feed, fetch_list=[loss])

    # the device only materializes at run time — fall back to CPU when the
    # accelerator path fails end to end
    try:
        import jax

        has_accel = any(d.platform != "cpu" for d in jax.local_devices())
    except Exception:
        has_accel = False
    dev = "TPU" if has_accel else "CPU"
    try:
        _try(fluid.TPUPlace(0) if has_accel else fluid.CPUPlace())
    except Exception:
        if not has_accel:
            raise
        dev = "CPU"
        _try(fluid.CPUPlace())
    print("Your paddle_tpu works well on %s." % dev)
    print("Your paddle_tpu is installed successfully! Let's start deep "
          "learning with paddle_tpu now.")
