"""DataFeeder: convert user data (numpy / lists) to feed dicts.

Parity: python/paddle/fluid/data_feeder.py.  LoD ragged inputs become padded
dense batches (TPU static shapes); lod metadata is preserved on the TpuTensor
when needed.
"""

import numpy as np

from .framework import Variable, dtype_to_np

__all__ = ["DataFeeder"]


class DataToTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.dtype = dtype
        self.data = []

    def feed(self, data):
        self.data.append(data)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=dtype_to_np(self.dtype))
            if self.shape is not None:
                concrete = [d for d in self.shape if d != -1]
                if len(concrete) == len(self.shape):
                    arr = arr.reshape([-1] + list(self.shape)[1:]) if -1 in self.shape else arr
            return arr
        np_dtype = dtype_to_np(self.dtype)
        if self.lod_level >= 2:
            # nested sequences (reference LoD level 2, lod_tensor.h:52):
            # list-of-lists-of-seqs -> [B, S, T, ...] padded
            from .lod import pad_nested_sequences

            out, _nseq, _lens = pad_nested_sequences(self.data, np_dtype)
            return out
        # ragged: pad to max length (lod.pad_sequences is the one
        # implementation of the padding rule)
        from .lod import pad_sequences

        out, _lens = pad_sequences(
            [np.asarray(s, dtype=np_dtype) for s in self.data], np_dtype)
        return out


class DataFeeder:
    def __init__(self, feed_list, place, program=None):
        from .framework import default_main_program

        self.place = place
        program = program or default_main_program()
        self.feed_names = []
        self.feed_shapes = []
        self.feed_dtypes = []
        self.feed_lod_level = []
        for each in feed_list:
            if isinstance(each, str):
                each = program.global_block().var(each)
            if not isinstance(each, Variable):
                raise TypeError("feed_list items must be Variable or str")
            self.feed_names.append(each.name)
            self.feed_shapes.append(each.shape)
            self.feed_dtypes.append(each.dtype)
            self.feed_lod_level.append(each.lod_level)

    def feed(self, iterable):
        converters = [
            DataToTensorConverter(self.place, lod, shape, dtype)
            for lod, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes
            )
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "sample has %d slots, feeder expects %d"
                % (len(each_sample), len(converters))
            )
            for value, conv in zip(each_sample, converters):
                conv.feed(value)
        return {
            name: conv.done()
            for name, conv in zip(self.feed_names, converters)
        }

    def feed_parallel(self, iterable, num_places=None):
        return [self.feed(chunk) for chunk in iterable]
