"""Benchmarks for the BASELINE.md target configs, driver-visible as JSON.

Default (driver) metric: ResNet-50 training throughput on one chip
(BASELINE config 2).  `BENCH_CONFIG` selects the others:

    BENCH_CONFIG=resnet50  (default)   images/sec/chip + MFU
    BENCH_CONFIG=bert                  seqs/sec/chip + model TF/s (config 3)
    BENCH_CONFIG=nmt                   tokens/sec (config 4)
    BENCH_CONFIG=scaling               1->N chip scaling efficiency (config 5;
                                       on a 1-chip host this runs the 8-way
                                       virtual CPU mesh as a smoke + emits
                                       the single-chip reference number)
    BENCH_CONFIG=longctx               long-context flash attention fwd+bwd
                                       tokens/s vs the XLA-composed path
                                       (BENCH_SEQ selects sequence length;
                                       vs_baseline=-1 = composed path OOMs)

Each run prints ONE JSON line {"metric","value","unit","vs_baseline"}.

Anchors: H100 ResNet-50 train ~3000 img/s/chip (NVIDIA NGC MLPerf-era
mixed-precision single-GPU; the former 2400 figure was generous), BERT-base
seq128 pretrain ~2300 seqs/s/chip (NGC LAMB phase-1 class), Transformer-base
NMT ~200k tokens/s/chip (see bench_nmt for the derivation).  Device roofline
(round-4 CORRECTED, measured with dependency-chained scans + optimization
barriers + RTT subtracted — tools/bench_dot_probe.py, bench_conv_probe.py,
bench_layout_probe.py): **193 TF/s bf16 matmul peak (8192^3), 155-164 TF/s
at BERT-shape dots, 700-886 GB/s reduce/stream HBM bandwidth** — a
full-spec v5e.  The round-2 "83 TF/s / 65-150 GB/s" numbers were a
tunnel-RTT measurement artifact (every dispatch+fetch pays ~95-120 ms of
host round trip); they are falsified and must not be cited.
Protocol per BASELINE.md: warmup, then median of timed chunks.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

H100_RESNET50_IMG_PER_SEC = 3000.0
H100_BERT_SEQ_PER_SEC = 2300.0
V5E_BF16_PEAK_TFLOPS = 197.0  # spec sheet; measured tunnel peak is lower


def _device():
    import paddle_tpu as fluid

    return fluid.TPUPlace(0).jax_device()


# executor_cache_miss_total delta across the TIMED region of the last
# _timed_loop (post-warmup).  This is the BENCH "recompiles" number: any
# miss after warmup means an executable was built inside the timed window
# and the median is invalid (BASELINE.md round-8 protocol).  A second
# same-process run of a config trivially reports 0 — the in-memory cache
# serves every step — and with FLAGS_compile_cache_dir armed a second
# PROCESS reports compile_ms_cold ~0 as well (tier-B restore).
_TIMED_RECOMPILES = None


def _miss_total():
    try:
        from paddle_tpu import telemetry
        return int(telemetry.counter_total("executor_cache_miss_total"))
    except Exception:
        return 0


def _telemetry_stats():
    """Step stats from the runtime metrics registry (core/telemetry.py).

    The executor records per-step wall time and compile time into the
    registry; the headline seqs/img numbers stay on _timed_loop's chunked
    host timing (the tunnel-RTT amortization is load-bearing — see
    _timed_loop), and these registry keys ride along so a BENCH JSON also
    says how much was spent compiling, whether anything RECOMPILED
    mid-run (a recompile inside the timed region invalidates the median),
    and what the per-step distribution looked like.  Empty when
    FLAGS_telemetry is off.

    Compile latency splits two ways (the persistent-cache story):
    ``compile_ms_cold`` is real trace+lower+XLA time paid this process;
    ``compile_ms_warm`` is tier-B disk-restore time.  A cold process
    reports (cold>0, warm=0); re-running the same config against the same
    FLAGS_compile_cache_dir flips it to (cold~0, warm=restore-ms)."""
    try:
        from paddle_tpu import telemetry
    except Exception:
        return {}
    if not telemetry.enabled():
        return {}
    snap = telemetry.snapshot()
    hists = snap.get("histograms", {})
    out = {"recompiles": int(_TIMED_RECOMPILES
                             if _TIMED_RECOMPILES is not None
                             else telemetry.counter_total(
                                 "executor_cache_miss_total"))}
    cold = sum(hists.get(k, {}).get("sum", 0.0)
               for k in ("executor_trace_lower_ms", "executor_xla_compile_ms"))
    warm = hists.get("compile_cache_load_ms", {}).get("sum", 0.0)
    out["compile_ms_cold"] = round(cold, 1)
    out["compile_ms_warm"] = round(warm, 1)
    comp = hists.get("executor_compile_ms")
    if comp:
        out["compile_ms"] = round(comp["sum"], 1)
    step = hists.get("executor_step_ms")
    if step:
        out["step_ms_p50"] = step["p50"]
        out["step_ms_p90"] = step["p90"]
        out["step_ms_p99"] = step["p99"]
    return out


def _timed_loop(run_step, sync, warmup, iters, chunk=None):
    # The axon tunnel costs ~95-120 ms per dispatch+fetch round trip (the
    # host-sync at each chunk boundary).  At chunk=5 that is ~21 ms/step of
    # pure tunnel artifact on top of ~210 ms device time — and its jitter
    # is the round-3 "2160 vs 2202" capture variance.  The default
    # BENCH_CHUNK=30 amortizes it to ~3.5 ms/step; the RTT is a property
    # of the test tunnel, not the chip, so deeper chunks are the more
    # honest steady-state measurement.  Numbers are only comparable across
    # rounds at the same chunk — BASELINE.md rows record it.
    if chunk is None:
        chunk = int(os.environ.get("BENCH_CHUNK", "30"))
    out = None
    for _ in range(warmup):
        out = run_step()
    if out is not None:
        sync(out)
    miss0 = _miss_total()
    times = []
    for _ in range(max(iters // chunk, 1)):
        t0 = time.perf_counter()
        for _ in range(chunk):
            out = run_step()
        sync(out)
        times.append((time.perf_counter() - t0) / chunk)
    global _TIMED_RECOMPILES
    _TIMED_RECOMPILES = _miss_total() - miss0
    return float(np.median(times)), out


def bench_resnet(batch=512, image_size=224, warmup=5, iters=30, depth=50,
                 amp=True, data_format="NCHW", chunk=None):
    """The headline config measures at chunk=120 (set by main): at ~211 ms
    device step the tunnel's ~100 ms dispatch+fetch RTT costs 3.3 ms/step
    at chunk=30 but 0.8 ms/step at chunk=120 — the steady-state device
    number a real training loop (which syncs rarely) sees.  Numbers are
    only comparable at matched chunk (BASELINE.md records it)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, label, loss, acc = resnet.build_train(
            depth=depth, class_dim=1000, image_size=image_size, lr=0.1,
            amp=amp, data_format=data_format)

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    use_pipeline = os.environ.get("BENCH_PIPELINE", "0") == "1"
    with fluid.scope_guard(scope):
        exe.run(startup)
        if use_pipeline:
            # full reference workflow: host batches ride the DataLoader's
            # native queue + double buffering (VERDICT r1 weak #8 — the
            # headline number with the input pipeline engaged)
            loader = fluid.io.DataLoader.from_generator(
                feed_list=[img, label], capacity=8, use_double_buffer=True)
            xs = rng.rand(batch, 3, image_size, image_size).astype("float32")
            ys = rng.randint(0, 1000, (batch, 1)).astype("int32")

            def gen():
                while True:
                    yield [xs, ys]

            loader.set_batch_generator(gen, places=[fluid.TPUPlace(0)])
            it = iter(loader)

            def step():
                feed = next(it)
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)
                return out
        else:
            xb = jax.device_put(
                rng.rand(batch, 3, image_size, image_size).astype("float32"),
                _device())
            yb = jax.device_put(
                rng.randint(0, 1000, (batch, 1)).astype("int32"), _device())
            feed = {"img": xb, "label": yb}

            def step():
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)
                return out

        med, out = _timed_loop(step, lambda o: np.asarray(o), warmup,
                               iters, chunk=chunk)
    return batch / med, float(np.asarray(out).reshape(-1)[0])


def _resnet50_train_flops_per_image(image_size=224):
    # fwd ~4.09 GFLOP/img at 224 (canonical count, MACs*2); train = fwd +
    # dgrad + wgrad ~ 3x fwd
    return 3 * 4.089e9 * (image_size / 224.0) ** 2


def _bert_feed(rng, cfg, batch, seq_len, mask_frac=0.15):
    n_mask = max(int(batch * seq_len * mask_frac), 1)
    return {
        "src_ids": rng.randint(0, cfg.vocab_size,
                               (batch, seq_len, 1)).astype("int64"),
        "pos_ids": np.tile(np.arange(seq_len).reshape(1, seq_len, 1),
                           (batch, 1, 1)).astype("int64"),
        "sent_ids": np.zeros((batch, seq_len, 1), "int64"),
        "input_mask": np.ones((batch, seq_len, 1), "float32"),
        "mask_pos": rng.randint(0, batch * seq_len, (n_mask,)).astype("int64"),
        "mask_label": rng.randint(0, cfg.vocab_size,
                                  (n_mask, 1)).astype("int64"),
    }


def bench_bert(batch=256, seq_len=128, warmup=3, iters=15, amp=True,
               use_amp_decorator=True):
    """Returns (seqs/s, loss, achieved_batch, stable).

    ``stable`` is True iff the FIRST attempt at the requested batch
    completed — i.e. the number is repeatable run to run at that batch.
    Round 5 sat within ~1% of the 16G HBM at bs256 and the allocator
    tipped over NONDETERMINISTICALLY (same binary: 1194.5 seqs/s one run,
    ResourceExhausted the next — BASELINE.md r5 note); the bf16 param
    carry + concat-free fused_adam reclaim that margin.  On OOM the SAME
    batch retries once with activation remat (BENCH_REMAT=auto default;
    =1 forces remat on the first attempt, =0 never uses it) before the
    batch shrinks 240 -> 224 -> 192."""
    import subprocess as _sp
    import sys as _sys

    remat_env = os.environ.get("BENCH_REMAT", "auto")
    remat0 = remat_env == "1"
    attempts = [(batch, remat0)]
    if remat_env == "auto":
        attempts.append((batch, True))
    attempts += [(x, remat0) for x in (240, 224, 192) if x < batch]
    last_err = ""
    for i, (b, rm) in enumerate(attempts):
        if i == 0:
            try:
                r = _bench_bert_at(b, seq_len, warmup, iters, amp, remat=rm)
                return r[0], r[1], b, True
            except Exception as e:
                if "RESOURCE_EXHAUSTED" not in str(e):
                    raise
                last_err = str(e)[:300]
            # free as much of the failed attempt as the runtime allows
            # before a retry shares the chip with this process
            try:
                import gc

                import jax

                gc.collect()
                jax.clear_caches()
            except Exception:
                pass
        else:
            # fresh SUBPROCESS per retry: a failed in-process attempt
            # pins its device buffers somewhere in the runtime (gc +
            # jax.clear_caches measured insufficient — every smaller
            # retry OOMed in-process while the same batch ran fine in a
            # fresh interpreter)
            code = ("import bench; r = bench._bench_bert_at(%d, %d, %d, "
                    "%d, %s, remat=%s); print('BENCH_RESULT', r[0], r[1], "
                    "bench._BERT_WIRE_BYTES)"
                    % (b, seq_len, warmup, iters, amp, rm))
            p = _sp.run([_sys.executable, "-c", code],
                        capture_output=True, text=True,
                        cwd=os.path.dirname(os.path.abspath(__file__)))
            for line in p.stdout.splitlines():
                if line.startswith("BENCH_RESULT"):
                    parts = line.split()
                    global _BERT_WIRE_BYTES
                    _BERT_WIRE_BYTES = (float(parts[3])
                                        if len(parts) > 3 else 0.0)
                    return float(parts[1]), float(parts[2]), b, False
            full = (p.stderr or "") + (p.stdout or "")
            last_err = full[-300:]
            # search the FULL output: TPU OOMs append a multi-KB hbm
            # allocation dump after the RESOURCE_EXHAUSTED line
            if "RESOURCE_EXHAUSTED" not in full:
                raise RuntimeError("bench_bert subprocess bs%d failed: %s"
                                   % (b, last_err))
        print("bench_bert: bs%d%s OOM, retrying" % (b, "+remat" if rm
                                                    else ""),
              file=_sys.stderr)
    raise RuntimeError("bench_bert: all batch sizes OOMed: %s" % last_err)


# analytic ICI wire bytes per step of the last _bench_bert_at program —
# stamped by the collective transpiler into _collective_meta (0.0 when the
# bench ran single-device / untranspiled)
_BERT_WIRE_BYTES = 0.0


def _bench_bert_at(batch, seq_len, warmup, iters, amp, remat=False):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    cfg = bert.BERT_BASE
    # build_pretrain's structure with an AMP-decorated Adam (the r1-recorded
    # config: bs256 seq128 AMP + flash attention)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        enc = bert.bert_encoder(cfg, seq_len, return_checkpoints=remat)
        if remat:
            inputs, seq_out, ckpts = enc
        else:
            inputs, seq_out = enc
        mask_pos = fluid.layers.data("mask_pos", shape=[1], dtype="int64")
        mask_label = fluid.layers.data("mask_label", shape=[1],
                                       dtype="int64")
        flat = fluid.layers.reshape(seq_out, [-1, cfg.hidden])
        picked = fluid.layers.gather(flat, mask_pos)
        trans = fluid.layers.fc(picked, cfg.hidden, act="gelu")
        trans = fluid.layers.layer_norm(trans, begin_norm_axis=1)
        logits = fluid.layers.fc(trans, cfg.vocab_size)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, mask_label))
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        if amp:
            opt = fluid.contrib.mixed_precision.decorate(opt)
        if remat:
            # remat wraps OUTSIDE the AMP decorator: RecomputeOptimizer
            # records the checkpoints on the program before delegating, and
            # the decorated minimize drives backward (which consumes them)
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(ckpts)
        opt.minimize(loss)

    # BENCH_COLLECTIVE=1: run the data-parallel exchange path (GradAllReduce
    # or, under FLAGS_collective_mode=zero1, ShardedGradAllReduce +
    # quantized wire per FLAGS_allreduce_dtype) over the local mesh and
    # report the transpiler's analytic bytes-on-ICI per step
    global _BERT_WIRE_BYTES
    _BERT_WIRE_BYTES = 0.0
    if os.environ.get("BENCH_COLLECTIVE", "0") == "1":
        n = len(jax.devices())
        if n > 1:
            from paddle_tpu.transpiler.collective import \
                select_grad_transpiler

            eps = ["local:%d" % i for i in range(n)]
            select_grad_transpiler().transpile(
                startup_program=startup, main_program=main, rank=0,
                endpoints=eps, current_endpoint=eps[0], wait_port=False)
            _BERT_WIRE_BYTES = float(
                main._collective_meta.get("wire_bytes_per_step", 0.0))

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = _bert_feed(rng, cfg, batch, seq_len)
    feed = {k: jax.device_put(v, _device()) for k, v in feed.items()}
    with fluid.scope_guard(scope):
        exe.run(startup)

        def step():
            out, = exe.run(main, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        med, out = _timed_loop(step, lambda o: np.asarray(o), warmup, iters)
    return batch / med, float(np.asarray(out).reshape(-1)[0])


def _bert_train_flops_per_seq(seq_len=128, layers=12, hidden=768,
                              vocab=30522):
    # encoder matmul flops/seq fwd: 12 * (4*h^2*2 (qkv+proj) + 2*4h*h*2
    # (ffn)) * s + attention 2*2*s^2*h; head: s*h*vocab*2; train = 3x
    per_layer = (4 * hidden * hidden * 2 + 2 * 4 * hidden * hidden * 2)
    enc = layers * (per_layer * seq_len + 2 * 2 * seq_len * seq_len * hidden)
    head = seq_len * hidden * vocab * 2
    return 3 * (enc + head)


def _nmt_train_flops_per_token(src_len=64, tgt_len=64, d=512, ffn=2048,
                               enc_layers=6, dec_layers=6, vocab=30000):
    # transformer-base matmul flops per batch element, fwd; train = 3x.
    # enc layer/token: qkv+proj 4*d^2*2, ffn 2*(d*ffn*2); dec layer adds
    # cross-attention projections (another 4*d^2*2); head: d*vocab*2 per
    # TARGET token; attention scores 2*2*span*d per token, where the span
    # is tgt_len for decoder self-attention but SRC_len for
    # cross-attention (the decoder attends over the encoder sequence).
    enc_tok = 4 * d * d * 2 + 2 * d * ffn * 2 + 2 * 2 * src_len * d
    dec_tok = (8 * d * d * 2 + 2 * d * ffn * 2
               + 2 * 2 * tgt_len * d + 2 * 2 * src_len * d)
    fwd = (src_len * enc_layers * enc_tok + tgt_len * dec_layers * dec_tok
           + tgt_len * d * vocab * 2)
    return 3 * fwd / (src_len + tgt_len)


# H100 transformer-base NMT anchor, derived (BASELINE.md config 4 note):
# the recorded H100 BERT anchor implies 2300 seqs/s * 85 GFLOP/seq =
# ~196 TF/s = ~20% MFU of the 989 TF/s bf16 peak; applying that SAME MFU
# to transformer-base's train FLOPs/token gives the tokens/s an H100
# would post on this config.  This is generous to the H100 (small d=512 /
# seq-64 models run at LOWER MFU than BERT-base), hence an honest upper
# anchor.  Note the physics: H100:v5e peak ratio is ~5:1, so any
# compute-bound config on ONE chip is bounded near vs_baseline ~0.2
# at matched MFU (the BERT r1 note; ResNet escapes it by being
# bandwidth-bound on the H100).
H100_NMT_TOKENS_PER_SEC = (H100_BERT_SEQ_PER_SEC * _bert_train_flops_per_seq()
                           / _nmt_train_flops_per_token())


def bench_nmt(batch=128, src_len=64, tgt_len=64, warmup=3, iters=15):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    # transformer-base (config 4 as recorded in BASELINE.md r1)
    cfg = transformer.TransformerConfig(
        src_vocab=30000, trg_vocab=30000, d_model=512, heads=8,
        enc_layers=6, dec_layers=6, ffn=2048, max_len=max(src_len, tgt_len))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, loss = transformer.build_train(cfg, src_len, tgt_len)

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(2, cfg.src_vocab,
                               (batch, src_len)).astype("int64"),
        "trg_ids": rng.randint(2, cfg.trg_vocab,
                               (batch, tgt_len)).astype("int64"),
        "trg_next": rng.randint(2, cfg.trg_vocab,
                                (batch, tgt_len)).astype("int64"),
        "trg_weight": np.ones((batch, tgt_len), "float32"),
    }
    feed = {k: jax.device_put(v, _device()) for k, v in feed.items()}
    with fluid.scope_guard(scope):
        exe.run(startup)

        def step():
            out, = exe.run(main, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        med, out = _timed_loop(step, lambda o: np.asarray(o), warmup, iters)
    tokens = batch * (src_len + tgt_len)
    return tokens / med, float(np.asarray(out).reshape(-1)[0])


def bench_longctx(seq_len=4096, batch=1, heads=12, head_dim=64, warmup=3,
                  iters=12, causal=True):
    """Long-context attention (the new-capability tier, SURVEY §5): fwd+bwd
    through the Pallas flash kernel at long sequence vs the XLA-composed
    reference path; reports tokens/s and the speedup."""
    import importlib
    import time as _time

    import jax
    import jax.numpy as jnp

    fa = importlib.import_module("paddle_tpu.pallas_kernels.flash_attention")
    rng = np.random.RandomState(0)
    shape = (batch, heads, seq_len, head_dim)
    q, k, v = (jax.device_put(rng.uniform(-1, 1, shape).astype("float32"),
                              _device()).astype(jnp.bfloat16)
               for _ in range(3))

    def make_loss(attn):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v, causal=causal).astype(jnp.float32)
                           ** 2)
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def timeit(fn):
        g = fn(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(g)[0].ravel()[0:1])
        for _ in range(warmup):
            g = fn(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(g)[0].ravel()[0:1])
        t0 = _time.perf_counter()
        for _ in range(iters):
            g = fn(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(g)[0].ravel()[0:1])
        return (_time.perf_counter() - t0) / iters

    t_flash = timeit(make_loss(
        lambda q, k, v, causal: fa.flash_attention(q, k, v, causal=causal)))
    try:
        t_ref = timeit(make_loss(
            lambda q, k, v, causal: fa._ref_attention(
                q, k, v, None, causal, q.shape[-1] ** -0.5)))
        speedup = t_ref / t_flash
    except Exception as e:
        # the composed path materializes the [S, S] score matrix and OOMs
        # at long sequence — the capability gap the flash kernel closes.
        # Anything that is NOT an out-of-memory failure is a real bug and
        # must surface.
        msg = str(e)
        if not ("RESOURCE_EXHAUSTED" in msg or "out of memory" in msg
                or "Ran out of memory" in msg):
            raise
        speedup = float("inf")
    toks = batch * seq_len / t_flash
    return toks, speedup, seq_len


def bench_scaling(batch_per_chip=512, warmup=3, iters=9):
    """Config 5: data-parallel ResNet-50 scaling efficiency across the local
    mesh (fleet Collective path -> shard_map + psum over ICI).  On the
    1-chip bench host this measures 1-chip throughput and emits
    efficiency=1.0 with n_devices=1; on a pod slice it measures 1 vs N.
    A CPU-mesh smoke of the same path runs in tests/test_collective.py."""
    import jax

    n = len([d for d in jax.devices() if d.platform != "cpu"]) or 1

    def run(nchips):
        import paddle_tpu as fluid
        from paddle_tpu.models import resnet

        batch = batch_per_chip * nchips
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img, label, loss, acc = resnet.build_train(
                depth=50, class_dim=1000, image_size=224, lr=0.1, amp=True)
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe = fluid.Executor(fluid.TPUPlace(0))
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        # stage once on device: the tunneled bench host moves ~11 MB/s, so
        # per-step host feeds would measure the link, not the collectives
        xb = jax.device_put(
            rng.rand(batch, 3, 224, 224).astype("float32"), _device())
        yb = jax.device_put(
            rng.randint(0, 1000, (batch, 1)).astype("int32"), _device())
        with fluid.scope_guard(scope):
            exe.run(startup)
            feed = {"img": xb, "label": yb}

            def step():
                out, = exe.run(cp, feed=feed, fetch_list=[loss],
                               return_numpy=False)
                return out

            # chunk must equal bench_resnet's: the per-chunk host sync
            # rides the slow tunnel, and a different amortization showed
            # up as a phantom 7-15% "SPMD overhead" in round 2 (at a
            # matched harness the shard_map path is at parity)
            med, _ = _timed_loop(step, lambda o: np.asarray(o), warmup,
                                 iters)
        return batch / med

    one = run(1)
    if n == 1:
        return 1.0, one, 1, one
    full = run(n)
    return full / (one * n), full, n, one


def bench_serving(requests=300, qps=80.0, buckets="1,4,16"):
    """Continuous-batching serving under open-loop Poisson load
    (serving/engine.py behind the RPC frontend, driven by
    tools/loadgen.py).  Measures end-to-end request latency through the
    admission queue + bucketed batcher, not bare executor dispatch; all
    buckets AOT-prewarm first, so `recompiles` counts executables built
    under TRAFFIC — the round-10 capture protocol marks any nonzero
    value invalid."""
    import sys
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import loadgen
    from serve import save_demo_model

    from paddle_tpu.serving import ServingEngine, ServingServer

    tmp = tempfile.mkdtemp(prefix="bench_serving_")
    model_dir = save_demo_model(os.path.join(tmp, "model"))
    engine = ServingEngine(buckets=buckets)
    engine.add_model("fc", model_dir)
    manifest = engine.prewarm()
    miss0 = _miss_total()
    server = ServingServer(engine, port=0).start()
    out_json = os.path.join(os.getcwd(), "BENCH_serving.json")
    try:
        rc = loadgen.main([
            "--endpoints", "127.0.0.1:%d" % server.port, "--model", "fc",
            "--requests", str(requests), "--qps", str(qps),
            "--batch-mix", "1,1,2,4,8", "--out", out_json])
        assert rc == 0, "loadgen failed"
    finally:
        server.shutdown()
    with open(out_json) as f:
        report = json.load(f)
    report["recompiles"] = _miss_total() - miss0
    report["prewarm"] = manifest
    with open(out_json, "w") as f:
        json.dump(report, f, indent=1)
    return report


def main():
    # arm the metrics registry before the lazy paddle_tpu import (flags
    # read FLAGS_* env at import time; env also reaches the bench_bert
    # OOM-retry subprocesses).  BENCH_TELEMETRY=0 opts out.
    if os.environ.get("BENCH_TELEMETRY", "1") == "1":
        os.environ.setdefault("FLAGS_telemetry", "1")
    # persistent two-tier compilation cache (core/compile_cache.py): on by
    # default so a repeat of the same config pays compile_ms_cold ~0 —
    # restore from disk instead of XLA.  BENCH_COMPILE_CACHE=<dir> picks
    # the location, ="" disables; env (not set_flags) so the bench_bert
    # OOM-retry subprocesses share it.
    cc_dir = os.environ.get("BENCH_COMPILE_CACHE")
    if cc_dir is None:
        import tempfile

        cc_dir = os.path.join(tempfile.gettempdir(), "paddle_tpu_bench_cc")
    if cc_dir:
        os.environ.setdefault("FLAGS_compile_cache_dir", cc_dir)
    cfg = os.environ.get("BENCH_CONFIG", "resnet50")
    iters = int(os.environ.get("BENCH_ITERS", "60"))
    if cfg == "bert":
        batch = int(os.environ.get("BENCH_BATCH", "256"))
        seqs, _loss, got_batch, stable = bench_bert(batch=batch,
                                                    iters=max(iters // 2, 5))
        tfs = seqs * _bert_train_flops_per_seq() / 1e12
        rec = {
            "metric": "bert_base_pretrain_seqs_per_sec_per_chip",
            "value": round(seqs, 2),
            "unit": "seqs/sec",
            "vs_baseline": round(seqs / H100_BERT_SEQ_PER_SEC, 4),
            "model_tflops_per_sec": round(tfs, 1),
            "mfu_vs_v5e_peak": round(tfs / V5E_BF16_PEAK_TFLOPS, 4),
            # the HBM-edge fallback may have reduced the batch: per-chip
            # throughput is still comparable, but record what actually ran
            "batch": got_batch,
            # stable = the FIRST attempt at the requested batch completed
            # (no OOM fallback fired), i.e. the number is repeatable at
            # this batch run to run — see bench_bert
            "stable": stable,
            # analytic per-rank ICI wire bytes per step of the gradient
            # exchange (BENCH_COLLECTIVE=1 + multi-device; else 0.0).
            # FLAGS_allreduce_dtype=int8 should read ~0.25x the f32 row;
            # FLAGS_collective_mode=zero1 at f32 matches replicated (the
            # RS+AG pair costs exactly one ring allreduce)
            "bytes_on_ici_per_step": round(_BERT_WIRE_BYTES, 1),
        }
        if stable:
            # on the OOM-fallback path the number came from a retry
            # subprocess, so this process's registry holds the FAILED
            # attempt — only merge when the stats describe the run
            rec.update(_telemetry_stats())
        print(json.dumps(rec))
    elif cfg == "nmt":
        batch = int(os.environ.get("BENCH_BATCH", "128"))
        toks, _loss = bench_nmt(batch=batch, iters=max(iters // 2, 5))
        tfs = toks * _nmt_train_flops_per_token() / 1e12
        print(json.dumps(dict({
            "metric": "transformer_nmt_tokens_per_sec_per_chip",
            "value": round(toks, 2),
            "unit": "tokens/sec",
            # anchor: H100 at its BERT-anchor MFU applied to this model's
            # FLOPs/token (derivation at H100_NMT_TOKENS_PER_SEC; ~0.2 is
            # the peak-ratio bound for compute-bound 1-chip configs)
            "vs_baseline": round(toks / H100_NMT_TOKENS_PER_SEC, 4),
            "model_tflops_per_sec": round(tfs, 1),
            "mfu_vs_v5e_peak": round(tfs / V5E_BF16_PEAK_TFLOPS, 4),
        }, **_telemetry_stats())))
    elif cfg == "serving":
        requests = int(os.environ.get("BENCH_REQUESTS", "300"))
        qps = float(os.environ.get("BENCH_QPS", "80"))
        rep = bench_serving(requests=requests, qps=qps)
        print(json.dumps({
            "metric": "serving_p99_latency_ms",
            "value": rep["latency_ms_p99"],
            "unit": "ms",
            # under open-loop load the server must sustain what was
            # offered: achieved/offered QPS is the health ratio
            "vs_baseline": round(rep["achieved_qps"] / qps, 4),
            "latency_ms_p50": rep["latency_ms_p50"],
            "qps_under_load": rep["achieved_qps"],
            "batch_fill": rep["batch_fill"],
            "shed_rate": rep["shed_rate"],
            "dropped": rep["dropped"],
            "recompiles": rep["recompiles"],
        }))
    elif cfg == "longctx":
        seq = int(os.environ.get("BENCH_SEQ", "4096"))
        toks, speedup, seq = bench_longctx(seq_len=seq)
        print(json.dumps({
            "metric": "flash_attention_fwdbwd_tokens_per_sec_seq%d" % seq,
            "value": round(toks, 1),
            "unit": "tokens/sec",
            # vs XLA-composed attention; inf-> -1 = composed path OOMs
            "vs_baseline": (round(speedup, 3)
                            if speedup != float("inf") else -1),
        }))
    elif cfg == "scaling":
        eff, ips, n, one_chip = bench_scaling(iters=15)
        # single-chip shard_map vs plain-executor parity (round-2 verdict
        # perf item: on a pod the shard_map path IS the execution path, so
        # its 1-chip throughput must match the plain executor's).  Both
        # legs use the same _timed_loop harness (chunk=5, 3 chunks) — a
        # mismatched chunking previously read as a phantom 7-15% overhead
        plain_ips, _ = bench_resnet(batch=512, warmup=3, iters=15)
        print(json.dumps(dict({
            "metric": "resnet50_dp_scaling_efficiency",
            "value": round(eff, 4),
            "unit": "fraction_linear_%dchips" % n,
            "vs_baseline": round(eff / 0.90, 4),  # gate: >=90% linear
            "images_per_sec_total": round(ips, 2),
            "plain_images_per_sec": round(plain_ips, 2),
            "spmd_over_plain": round(one_chip / plain_ips, 4),
        }, **_telemetry_stats())))
    else:
        batch = int(os.environ.get("BENCH_BATCH", "512"))
        amp = os.environ.get("BENCH_AMP", "1") == "1"
        data_format = os.environ.get("BENCH_DATA_FORMAT", "NCHW")
        img_per_sec, _loss = bench_resnet(batch=batch,
                                          iters=max(iters, 240), amp=amp,
                                          chunk=int(os.environ.get(
                                              "BENCH_CHUNK", "120")),
                                          data_format=data_format)
        tfs = img_per_sec * _resnet50_train_flops_per_image() / 1e12
        from paddle_tpu.pallas_kernels import adoption

        print(json.dumps(dict({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": round(img_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(img_per_sec / H100_RESNET50_IMG_PER_SEC, 4),
            "model_tflops_per_sec": round(tfs, 1),
            "mfu_vs_v5e_peak": round(tfs / V5E_BF16_PEAK_TFLOPS, 4),
            # which Pallas fused-block kernels actually engaged during the
            # run (BASELINE.md round-9: a kernel adopted without a probe
            # row next to BENCH_*.json is an invalid capture)
            "pallas_kernels_active": adoption.active_kernels(),
        }, **_telemetry_stats())))


if __name__ == "__main__":
    main()
