"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: H100 ResNet-50 train throughput ~2400 img/s/chip (mixed precision,
bs256 — public MLPerf-era number); BASELINE.md gate is >=0.8x H100
throughput.  Protocol per BASELINE.md: warmup then timed steps, median.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

H100_RESNET50_IMG_PER_SEC = 2400.0


def bench_resnet(batch=512, image_size=224, warmup=5, iters=30, depth=50,
                 amp=True, data_format="NCHW"):
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, label, loss, acc = resnet.build_train(
            depth=depth, class_dim=1000, image_size=image_size, lr=0.1,
            amp=amp, data_format=data_format)

    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xb = rng.rand(batch, 3, image_size, image_size).astype("float32")
    yb = rng.randint(0, 1000, (batch, 1)).astype("int32")

    # stage the batch on device once (the DataLoader path double-buffers
    # host->device copies asynchronously; this measures compute throughput
    # with a warm input pipeline)
    import jax

    dev = fluid.TPUPlace(0).jax_device()
    xb = jax.device_put(xb, dev)
    yb = jax.device_put(yb, dev)

    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"img": xb, "label": yb}
        for _ in range(warmup):
            out, = exe.run(main, feed=feed, fetch_list=[loss],
                           return_numpy=False)
        np.asarray(out)  # sync after warmup
        # steps chain through the scope's param state; device-resident
        # fetches avoid a host round-trip per step (the loop is dispatch-
        # async exactly like a production input pipeline), with one sync at
        # each timing boundary.  Median over chunks per BASELINE.md.
        chunk = 5
        times = []
        for _ in range(max(iters // chunk, 1)):
            t0 = time.perf_counter()
            for _ in range(chunk):
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)
            np.asarray(out)  # block on the chunk
            times.append((time.perf_counter() - t0) / chunk)
    med = float(np.median(times))
    return batch / med, float(np.asarray(out).reshape(-1)[0])


def main():
    batch = int(os.environ.get("BENCH_BATCH", "512"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    data_format = os.environ.get("BENCH_DATA_FORMAT", "NCHW")
    img_per_sec, last_loss = bench_resnet(batch=batch, iters=iters, amp=amp,
                                          data_format=data_format)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / H100_RESNET50_IMG_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
