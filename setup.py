"""Packaging for paddle_tpu (reference layer 0: CMake build + wheel;
here the Python package + the native C++ runtime pieces, which
compile on first import via the system toolchain — see
paddle_tpu/native/__init__.py)."""

import os

from setuptools import find_packages, setup


def _read_version():
    return "0.2.0"  # round-2 snapshot


setup(
    name="paddle_tpu",
    version=_read_version(),
    description=("TPU-native deep-learning framework with the PaddlePaddle "
                 "v1.6 fluid capability surface: Program/Executor static "
                 "graphs compiled whole-block to XLA, dygraph, fleet "
                 "distribution, PS runtime, inference engine"),
    packages=find_packages(include=["paddle_tpu", "paddle_tpu.*"]),
    py_modules=["bench"],
    package_data={
        "paddle_tpu": ["native/csrc/*.cc", "native/csrc_capi/*.cc"],
    },
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    entry_points={
        "console_scripts": [
            "paddle-tpu-bench=bench:main",
        ],
    },
)
