#!/usr/bin/env python
"""Static concurrency lint (CC1xx) over the Python runtime.

Mirrors tools/proglint.py for the thread layer: AST-only analysis of
lock ordering, blocking-under-lock, guarded-state escapes, condition
waits, callback contracts, and thread lifecycle (see
paddle_tpu/core/concurrency_analysis.py for the rule catalog).

  tools/threadlint.py                      # lint paddle_tpu/, exit 0/1
  tools/threadlint.py --path paddle_tpu/serving
  tools/threadlint.py --rule CC101 --rule CC102
  tools/threadlint.py --dump json
  tools/threadlint.py --seed-defect cc101  # self-test: must exit 1
                                           # naming the exact file:line

Exit codes: 0 clean (all error/warning findings waived or none), 1 any
unwaived error/warning finding (for --seed-defect this is the SUCCESS
path), 2 self-test failure (seeded defect missed or misattributed).
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

_FIXTURES = os.path.join(_ROOT, "tests", "threadlint_fixtures")


def _seed_defect(rule, args):
    from paddle_tpu.core.concurrency_analysis import (
        analyze_paths, expected_findings)

    rule = rule.upper()
    path = os.path.join(_FIXTURES, "%s_seed.py" % rule.lower())
    if not os.path.exists(path):
        print("threadlint: no seeded fixture for %s (%s)" % (rule, path))
        return 2
    expected = [(r, ln) for r, ln in expected_findings(path) if r == rule]
    if not expected:
        print("threadlint: fixture %s carries no threadlint-expect "
              "markers for %s" % (path, rule))
        return 2
    report = analyze_paths([path], label="seeded %s fixture" % rule)
    print(report.format())
    got = {(d.rule, d.line) for d in report.diagnostics if not d.waived}
    missed = [e for e in expected if e not in got]
    if missed:
        print("threadlint: SELF-TEST FAILED — seeded %s not reported at %s"
              % (rule, ", ".join("%s:%d" % (os.path.relpath(path), ln)
                                 for _r, ln in missed)))
        return 2
    for r, ln in expected:
        print("threadlint: seeded defect detected: %s at %s:%d"
              % (r, os.path.relpath(path), ln))
    return 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static concurrency lint (CC1xx rules)")
    ap.add_argument("--path", action="append", default=None,
                    help="file or directory to lint (repeatable; "
                         "default: paddle_tpu)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to a rule id, e.g. CC101 (repeatable)")
    ap.add_argument("--dump", choices=("text", "json"), default="text")
    ap.add_argument("--strict", action="store_true",
                    help="info-level findings also fail the run")
    ap.add_argument("--seed-defect", default=None,
                    metavar="cc101",
                    help="analyze the seeded fixture for this rule; the "
                         "defect MUST be reported (exit 1) or the "
                         "self-test fails (exit 2)")
    args = ap.parse_args(argv)

    if args.seed_defect:
        return _seed_defect(args.seed_defect, args)

    from paddle_tpu.core.concurrency_analysis import (
        analyze_paths, report_telemetry)

    paths = args.path or [os.path.join(_ROOT, "paddle_tpu")]
    rules = [r.upper() for r in args.rule] if args.rule else None
    report = analyze_paths(paths, rules=rules,
                           label=", ".join(os.path.relpath(p)
                                           for p in paths))
    report_telemetry(report)
    if args.dump == "json":
        import json
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.format())
    if not report.ok:
        return 1
    if args.strict and report.infos:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
