"""Microbench: can a Pallas kernel beat XLA's reduction-read bandwidth cap?

Round-3 roofline measured XLA reduction-to-small-output reads at 60-76 GB/s
vs 128-147 GB/s for elementwise streams; BN statistics + wgrad reductions
(the convert_reduce fusion class) are 48% of the ResNet-50 step.  This
measures whether a hand-written Pallas channel reduction reads at the
stream rate, which would halve the dominant slice.

Protocol (the round-3 harness rules for the axon tunnel): dependency-chained
repetitions inside ONE jit call (a scalar carry folds into each iteration so
XLA cannot CSE), host-fetch sync via np.asarray (block_until_ready does not
wait on this platform), tunnel RTT measured separately and subtracted.

Usage: python tools/bench_reduce_pallas.py [variant ...]
"""

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from bench_util import timed as _time, tunnel_rtt as _rtt
from jax import lax
from jax.experimental import pallas as pl

# BN-stats shape at ResNet-50 bs512: conv output [512, 64, 56, 56] bf16 in
# NHWC view = [N*H*W, C].  c256 is the deeper-stage shape at equal bytes.
SHAPES = {
    "c64": (512 * 56 * 56, 64),
    "c256": (512 * 28 * 28, 256),
}
REP = 64  # chained passes per jit call


def _report(name, shape, t, rtt, passes=1.0):
    m, c = shape
    nbytes = m * c * 2 * REP * passes
    dev = max(t - rtt, 1e-9)
    gbs = nbytes / dev / 1e9
    print(f"{name:30s} {dev*1e3/REP:8.3f} ms/pass  {gbs:7.1f} GB/s")
    return gbs


# -- XLA column-reduce chain (the BN-stats emission) -------------------------

def jnp_stats(x):
    def body(c, _):
        xf = x.astype(jnp.float32) + c
        s = jnp.sum(xf, axis=0)
        ss = jnp.sum(xf * xf, axis=0)
        return (jnp.sum(s) + jnp.sum(ss)) * 1e-12, ()

    out, _ = lax.scan(body, jnp.float32(0.0), None, length=REP)
    return (out,)


# -- XLA elementwise stream chain (bandwidth reference) ----------------------

def jnp_stream(x, a):
    def body(y, _):
        return y * a, ()

    y, _ = lax.scan(body, x, None, length=REP)
    return (y[0, 0].astype(jnp.float32), y)


# -- Pallas column-reduce with grid accumulation -----------------------------

def _stats_kernel(x_ref, c_ref, s_ref, ss_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        ss_ref[...] = jnp.zeros_like(ss_ref)

    x = x_ref[...].astype(jnp.float32) + c_ref[0, 0]
    s_ref[...] += jnp.sum(x, axis=0, keepdims=True)
    ss_ref[...] += jnp.sum(x * x, axis=0, keepdims=True)


def pallas_stats_one(x, c, block_r):
    m, ch = x.shape
    s, ss = pl.pallas_call(
        _stats_kernel,
        grid=(m // block_r,),
        in_specs=[pl.BlockSpec((block_r, ch), lambda i: (i, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((1, ch), lambda i: (0, 0)),
                   pl.BlockSpec((1, ch), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, ch), jnp.float32),
                   jax.ShapeDtypeStruct((1, ch), jnp.float32)],
    )(x, c)
    return s, ss


def pallas_stats(x, block_r):
    def body(c, _):
        s, ss = pallas_stats_one(x, c, block_r)
        return (jnp.sum(s) + jnp.sum(ss)).reshape(1, 1) * 1e-12, ()

    out, _ = lax.scan(body, jnp.zeros((1, 1), jnp.float32), None, length=REP)
    return (out,)


# -- fused affine+stats: y = a*x+b written, stats of y collected -------------
# (models the BN epilogue producer-fusion: the stats pass stops re-reading)

def _affine_stats_kernel(x_ref, a_ref, b_ref, y_ref, s_ref, ss_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)
        ss_ref[...] = jnp.zeros_like(ss_ref)

    x = x_ref[...].astype(jnp.float32)
    y = x * a_ref[...] + b_ref[...]
    y_ref[...] = y.astype(y_ref.dtype)
    s_ref[...] += jnp.sum(y, axis=0, keepdims=True)
    ss_ref[...] += jnp.sum(y * y, axis=0, keepdims=True)


def pallas_affine_stats(x, a, b, block_r):
    m, ch = x.shape

    def body(y, _):
        y2, s, ss = pl.pallas_call(
            _affine_stats_kernel,
            grid=(m // block_r,),
            in_specs=[pl.BlockSpec((block_r, ch), lambda i: (i, 0)),
                      pl.BlockSpec((1, ch), lambda i: (0, 0)),
                      pl.BlockSpec((1, ch), lambda i: (0, 0))],
            out_specs=[pl.BlockSpec((block_r, ch), lambda i: (i, 0)),
                       pl.BlockSpec((1, ch), lambda i: (0, 0)),
                       pl.BlockSpec((1, ch), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((m, ch), x.dtype),
                       jax.ShapeDtypeStruct((1, ch), jnp.float32),
                       jax.ShapeDtypeStruct((1, ch), jnp.float32)],
        )(y, a, b)
        return y2, jnp.sum(s) + jnp.sum(ss)

    y, stats = lax.scan(body, x, None, length=REP)
    return (stats[-1], y)


# XLA equivalent: y = a*x+b, then stats of y (XLA may or may not
# producer-fuse the reduce into the affine — that is what we measure)

def jnp_affine_stats(x, a, b):
    def body(y, _):
        y2 = y * a[0].astype(y.dtype) + b[0].astype(y.dtype)
        yf = y2.astype(jnp.float32)
        s = jnp.sum(yf, axis=0)
        ss = jnp.sum(yf * yf, axis=0)
        return y2, jnp.sum(s) + jnp.sum(ss)

    y, stats = lax.scan(body, x, None, length=REP)
    return (stats[-1], y)


def main():
    want = set(_sys.argv[1:])
    print(f"device: {jax.devices()[0]}")
    rtt = _rtt()
    print(f"tunnel RTT: {rtt*1e3:.1f} ms (subtracted)")
    for sname, shape in SHAPES.items():
        m, c = shape
        print(f"-- shape [{m}, {c}] bf16 ({m*c*2/1e6:.0f} MB), REP={REP}")
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, shape, dtype=jnp.bfloat16)
        a = jnp.ones((1, c), jnp.float32) * 1.0000001
        b = jnp.zeros((1, c), jnp.float32)

        if not want or "stream" in want:
            t = _time(jnp_stream, x, jnp.bfloat16(1.0000001))
            _report("xla stream 1r1w", shape, t, rtt, passes=2.0)
        if not want or "jnp" in want:
            t = _time(jnp_stats, x)
            _report("xla sum+sumsq (reduce)", shape, t, rtt)
        if not want or "pallas" in want:
            for br in (512, 1024, 2048):
                if m % br:
                    continue
                t = _time(functools.partial(pallas_stats, block_r=br), x)
                _report(f"pallas sum+sumsq br={br}", shape, t, rtt)
        if not want or "fused" in want:
            t = _time(jnp_affine_stats, x, a, b)
            _report("xla affine+stats", shape, t, rtt, passes=3.0)
            for br in (512, 1024):
                if m % br:
                    continue
                t = _time(
                    functools.partial(pallas_affine_stats, block_r=br),
                    x, a, b)
                _report(f"pallas affine+stats br={br}", shape, t, rtt,
                        passes=2.0)


if __name__ == "__main__":
    main()
