"""ZeRO-1 dryrun payload for the `tools/run_ci.sh --zero1-smoke` leg.

Builds a small fc+Adam model, transpiles it through select_grad_transpiler
(honoring FLAGS_collective_mode / FLAGS_allreduce_dtype from the
environment), verifies it (the CI leg exports FLAGS_static_check=error so
any DL005/DL006 diagnostic is fatal), runs a few steps over the virtual
8-device mesh, and prints the shard table + analytic wire bytes.  Exits
non-zero if the sharded run diverges or no param actually sharded.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import flags  # noqa: E402
from paddle_tpu.core import analysis  # noqa: E402
from paddle_tpu.transpiler.collective import \
    select_grad_transpiler  # noqa: E402

NRANKS = 8
STEPS = 3


def main():
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data("x", shape=[16])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(x, 64, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Adam(0.01).minimize(loss)

    eps = ["local:%d" % i for i in range(NRANKS)]
    t = select_grad_transpiler()
    t.transpile(startup_program=startup, main_program=main_p, rank=0,
                endpoints=eps, current_endpoint=eps[0], wait_port=False)
    meta = main_p._collective_meta
    print("zero1_smoke: mode=%s dtype=%s wire_bytes_per_step=%.0f"
          % (meta["mode"], meta["allreduce_dtype"],
             meta["wire_bytes_per_step"]))

    # explicit verify on top of the FLAGS_static_check gate, so the smoke
    # fails loudly even when the env forgot to export the flag
    rep = analysis.verify_program(main_p, feed_names=["x", "y"],
                                  fetch_names=[loss.name],
                                  expected_nranks=NRANKS)
    if rep.errors:
        print(rep.format())
        return 1

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i in range(STEPS):
            xb = rng.randn(16, 16).astype(np.float32)
            yb = rng.randn(16, 1).astype(np.float32)
            lv, = exe.run(main_p, feed={"x": xb, "y": yb},
                          fetch_list=[loss.name])
            val = float(np.asarray(lv).reshape(-1)[0])
            print("zero1_smoke: step=%d loss=%.6f" % (i, val))
            if not np.isfinite(val):
                print("zero1_smoke: FAIL (non-finite loss)")
                return 1

    shards = meta.get("zero1_shards")
    if flags.flag("collective_mode") == "zero1":
        if not shards or not any(e["sharded"] for e in shards.values()):
            print("zero1_smoke: FAIL (nothing sharded)")
            return 1
        for p, e in sorted(shards.items()):
            print("zero1_smoke: shard %-24s %s" % (
                p, "rows/rank=%d bytes/rank=%d" % (
                    e["rows_per_rank"], e["bytes_per_rank"])
                if e["sharded"] else "replicated (%s)" % e["reason"]))
    print("zero1_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
