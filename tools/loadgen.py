"""Open-loop Poisson load generator for the serving stack
(tools/serve.py).

Open-loop means arrivals are scheduled from a Poisson process and fired
on time whether or not earlier requests finished — the discipline that
exposes queueing collapse, unlike closed-loop clients whose arrival rate
politely slows with the server.  Each arrival runs on its own thread so
a slow reply never delays the next arrival.

Feeds are synthesized from the server's ``__spec__`` RPC (zeros for
integer feeds, ones for floats) so the generator needs no model files.
Batch sizes are sampled from --batch-mix so traffic exercises several
buckets.

Emits one JSON report (default BENCH_serving.json): p50/p99 end-to-end
latency, per-phase p50/p99 attribution (queue_wait_ms / execute_ms from
the server's reply meta, wire_ms = client e2e minus server time — so a
p99 regression localizes to queueing, compute, or the wire), achieved
QPS under load, server-side mean batch fill, shed rate, and the dropped
count (requests no live endpoint answered).
--assert-no-drops makes a nonzero dropped count a nonzero exit — the CI
SIGKILL leg's invariant that elastic shrink loses no admitted requests.

When the model's ``__spec__`` says ``type: decode`` the generator
switches to autoregressive traffic: prompts of --prompt-mix lengths,
--max-new generated tokens each, fired through ``client.generate``
(streaming, so TTFT and inter-token latency are measured at the client).
The report gains token-level serving metrics: ``tokens_per_sec``
(aggregate generated-token throughput), ``ttft_ms_p50/p99`` and
``itl_ms_p50/p99``, plus the engine's batching mode — run once against
a ``--decode-mode token`` server and once against ``request`` to
measure the continuous-batching win on the same traffic.  Against a
speculating server (FLAGS_speculative_k > 0) the report also carries
``speculative_k``, the scraped ``spec_tokens_proposed/accepted`` totals
and their ``spec_acceptance_rate``, and ``outputs_sha256`` — a
fingerprint of every (prompt -> output tokens) pair, so the same seeded
traffic replayed with speculation on and off can assert bitwise-equal
output next to the tokens/sec comparison.

Against a disaggregated fleet (reply phases carry ``role: disagg``) the
report gains a ``role_phases`` block splitting the pipeline per role:
prefill-side queue wait + prefill compute, the sealed-block transfer
hop (``xfer_ms``), and the decode half's queue wait + execute — so a
TTFT p99 regression attributes to the prefill queue and an ITL p99
regression to the decode side, per the disagg capture protocol in
BASELINE.md.

``--tier-mix paid:0.35,free:0.65`` stamps each request with a sampled
SLO tier (the engine's deadline-weighted admission sheds low tiers
first); the report gains a per-tier breakdown with ``server_ms_p99``
(queue_wait + execute — the wire-noise-free p99 the overload leg
asserts on) and shed counts.  ``--canary-assert LABEL:FRAC`` exits
nonzero unless >= FRAC of ok replies were served by model version
LABEL (reply phases carry the resolved version) — the post-flip
consistency check; the report's ``versions`` map counts every resolved
version seen.

``--prefix-share F`` turns on shared-prefix traffic: a fraction F of
requests prepend one of ``--prefix-pool`` seeded common prefixes of
``--prefix-tokens`` tokens to their random tail — the system-prompt /
few-shot-template shape the engine's KV prefix cache exists for.  The
report then carries ``prefix_share``, ``prefix_tokens``, and
``prefix_cache_hit_rate`` (client-side exact: Σ cached_tokens from the
reply phases / Σ prompt tokens — scrape-window independent), plus the
scraped ``prefix_cache_hit_tokens`` counter.  Replaying the same seed
with ``FLAGS_prefix_cache`` on and off gives the cache-on/off TTFT and
tokens/sec comparison on bitwise-identical traffic (equal
``outputs_sha256`` is the parity precondition).

When any reply finished on a replica other than the one that started
it (client crash resume after a SIGKILL, or a drain/pressure session
hand-off the stream followed), the report gains a ``resume`` block:
resumed request count, total resumed tokens, per-session rows of
(prompt_len, resumed_tokens, cached_tokens), and
``reprefill_tokens_max`` — the worst-case tokens any destination had
to re-feed, which --migrate-smoke gates at under one KV block.
"""

import argparse
import hashlib
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synth_feeds(spec, rows):
    """Zero/one-filled feeds matching the server-published signature."""
    import numpy as np

    feeds = {}
    for name, s in spec["feeds"].items():
        dt = np.dtype(s["dtype"])
        shape = (rows,) + tuple(s["shape"])
        feeds[name] = np.zeros(shape, dt) if dt.kind in "iu" \
            else np.ones(shape, dt)
    return feeds


def percentile(xs, q):
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(int(q * len(s)), len(s) - 1)]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoints", default=None,
                    help="comma list of replica endpoints")
    ap.add_argument("--endpoints-file", default=None,
                    help="fleet endpoints file (failover re-reads it)")
    ap.add_argument("--model", required=True)
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--qps", type=float, default=50.0,
                    help="mean Poisson arrival rate")
    ap.add_argument("--batch-mix", default="1,1,2,4",
                    help="per-request row counts sampled uniformly")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--tenant", default="loadgen")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="BENCH_serving.json")
    ap.add_argument("--assert-no-drops", action="store_true",
                    help="exit 1 if any request was dropped (all "
                    "endpoint attempts failed)")
    ap.add_argument("--prompt-mix", default="2,4,8",
                    help="decode traffic: prompt lengths sampled "
                    "uniformly (mixed lengths exercise the shared "
                    "bucketed executable)")
    ap.add_argument("--max-new", type=int, default=8,
                    help="decode traffic: generated tokens per request")
    ap.add_argument("--no-stream", action="store_true",
                    help="decode traffic: skip per-token streaming "
                    "(TTFT/ITL then come from the server's phases)")
    ap.add_argument("--retry-shed", type=int, default=0,
                    help="resubmit a shed request up to N times after "
                    "its retry_after_ms hint")
    ap.add_argument("--tier-mix", default=None,
                    help="SLO-tiered traffic, e.g. paid:0.35,free:0.65 — "
                    "each request samples a tier by weight and the "
                    "report gains per-tier latency/shed breakdowns")
    ap.add_argument("--canary-assert", default=None, metavar="LABEL:FRAC",
                    help="exit 1 unless >= FRAC of ok replies were "
                    "served by model version LABEL (reply phases carry "
                    "the resolved version) — the post-flip check")
    ap.add_argument("--prefix-share", type=float, default=0.0,
                    help="decode traffic: fraction of requests whose "
                    "prompt starts with a shared common prefix drawn "
                    "from a small seeded pool (KV prefix-cache traffic)")
    ap.add_argument("--prefix-tokens", type=int, default=24,
                    help="length of each shared prefix")
    ap.add_argument("--prefix-pool", type=int, default=2,
                    help="number of distinct shared prefixes in the pool")
    args = ap.parse_args(argv)

    from paddle_tpu.serving import ServingClient

    endpoints = [e.strip() for e in (args.endpoints or "").split(",")
                 if e.strip()]
    client = ServingClient(endpoints=endpoints or None,
                           endpoints_file=args.endpoints_file,
                           tenant=args.tenant)
    spec = client.spec(args.model)
    decode = spec.get("type") == "decode"
    mix = [int(b) for b in args.batch_mix.split(",") if b]
    pmix = [int(b) for b in args.prompt_mix.split(",") if b]
    rng = random.Random(args.seed)

    vocab = int(spec.get("vocab", 2))
    # the shared-prefix pool is drawn from the SAME seeded rng before any
    # traffic, so two runs of one seed (cache-on vs cache-off) replay
    # bitwise-identical prompts
    prefixes = []
    if decode and args.prefix_share > 0:
        prefixes = [[rng.randrange(vocab)
                     for _ in range(args.prefix_tokens)]
                    for _ in range(args.prefix_pool)]

    # tiered traffic: sample each request's SLO tier by weight (seeded,
    # so two runs replay the same per-request tier assignment)
    tier_mix = []
    if args.tier_mix:
        for part in args.tier_mix.split(","):
            name, _, w = part.strip().partition(":")
            tier_mix.append((name, float(w or 1.0)))

    def sample_tier():
        if not tier_mix:
            return None
        x = rng.random() * sum(w for _, w in tier_mix)
        for name, w in tier_mix:
            x -= w
            if x <= 0:
                return name
        return tier_mix[-1][0]

    lock = threading.Lock()
    latencies, statuses = [], {}
    phase_samples = {"queue_wait_ms": [], "execute_ms": [], "wire_ms": []}
    # disaggregated replies attribute their phases per role: the prefill
    # half stamps prefill_queue_wait_ms/prefill_ms, the transfer hop
    # xfer_ms, and the standard queue_wait_ms/execute_ms then belong to
    # the DECODE half (reply phases carry role=disagg) — so a TTFT p99
    # regression localizes to prefill queueing, the stream, or decode
    role_phase = {"prefill_queue_wait_ms": [], "prefill_ms": [],
                  "xfer_ms": []}
    decode_phase = {"queue_wait_ms": [], "execute_ms": []}
    disagg_n = [0]
    # live-session migration attribution: a reply whose phases carry
    # resumed_tokens finished on a replica other than the one that
    # started it (crash resume or a drain/pressure hand-off the stream
    # followed) — rows feed the re-prefill gate in --migrate-smoke
    resume_rows = []
    ttfts, itls, tokens_out = [], [], [0]
    cached_toks, prompt_toks = [0], [0]   # client-side exact hit rate
    out_map = {}    # prompt tuple -> generated tokens (greedy => unique)
    # per-tier breakdown + per-version counts (phases carry the resolved
    # tier/model, so both attribute server-side)
    tier_stats = {}     # tier -> {requests, ok, shed, lat[], server[]}
    versions = {}       # resolved version name -> ok replies
    threads = []

    def run_once(rows, prompt, tier):
        if not decode:
            return client.infer(args.model, synth_feeds(spec, rows),
                                deadline_ms=args.deadline_ms, tier=tier)
        return client.generate(args.model, prompt,
                               max_new_tokens=args.max_new,
                               stream=not args.no_stream,
                               deadline_ms=args.deadline_ms, tier=tier)

    def fire(rows, prompt, tier):
        r = run_once(rows, prompt, tier)
        retries = args.retry_shed
        while r.status == "shed" and retries > 0:
            time.sleep(max(r.retry_after_ms, 1.0) / 1e3)
            retries -= 1
            r = run_once(rows, prompt, tier)
        with lock:
            statuses[r.status] = statuses.get(r.status, 0) + 1
            if tier is not None:
                ts = tier_stats.setdefault(
                    tier, {"requests": 0, "ok": 0, "shed": 0,
                           "lat": [], "server": []})
                ts["requests"] += 1
                if r.ok:
                    ts["ok"] += 1
                    ts["lat"].append(r.latency_ms)
                    # server-side time (queue + compute): the phase-p99
                    # the overload assert uses — wire/client noise-free
                    qw = r.phases.get("queue_wait_ms")
                    ex = r.phases.get("execute_ms")
                    if qw is not None and ex is not None:
                        ts["server"].append(float(qw) + float(ex))
                elif r.status == "shed":
                    ts["shed"] += 1
            if r.ok:
                v = r.phases.get("model")
                if v:
                    versions[v] = versions.get(v, 0) + 1
                latencies.append(r.latency_ms)
                for ph, xs in phase_samples.items():
                    v = r.phases.get(ph)
                    if v is not None:
                        xs.append(float(v))
                if r.phases.get("role") == "disagg":
                    disagg_n[0] += 1
                    for ph, xs in role_phase.items():
                        v = r.phases.get(ph)
                        if v is not None:
                            xs.append(float(v))
                    for ph, xs in decode_phase.items():
                        v = r.phases.get(ph)
                        if v is not None:
                            xs.append(float(v))
                if decode:
                    toks = list(int(t) for t in
                                r.outputs.get("tokens", ()))
                    tokens_out[0] += len(toks)
                    out_map[tuple(prompt)] = toks
                    cached_toks[0] += int(r.phases.get("cached_tokens", 0))
                    prompt_toks[0] += len(prompt)
                    if "resumed_tokens" in r.phases:
                        resume_rows.append({
                            "prompt_len": len(prompt),
                            "resumed_tokens":
                                int(r.phases["resumed_tokens"]),
                            "cached_tokens":
                                int(r.phases.get("cached_tokens", 0))})
                    # client-observed (wire-inclusive) when streaming,
                    # server-side phase attribution otherwise
                    ttft = r.phases.get("client_ttft_ms",
                                        r.phases.get("ttft_ms"))
                    if ttft is not None:
                        ttfts.append(float(ttft))
                    itls.extend(float(g) for g in r.phases.get(
                        "client_itl_ms_samples",
                        r.phases.get("itl_ms_samples", [])))

    t_start = time.perf_counter()
    next_at = t_start
    for _ in range(args.requests):
        next_at += rng.expovariate(args.qps)
        prompt = None
        if decode:
            # rng draw order matches the prefix-free generator when
            # --prefix-share is 0, so legacy seeded traffic is unchanged
            prompt = [rng.randrange(vocab)
                      for _ in range(rng.choice(pmix))]
            if prefixes and rng.random() < args.prefix_share:
                prompt = rng.choice(prefixes) + prompt
        delay = next_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=fire,
                             args=(rng.choice(mix), prompt, sample_tier()),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=120.0)
    wall_s = time.perf_counter() - t_start

    # server-side batch fill + speculation counters from the scrape
    # (best-effort: a SIGKILLed coordinator can leave no scrapeable
    # replica in tiny test fleets)
    batch_fill = None
    spec_proposed = spec_accepted = 0.0
    prefix_hit_scraped = 0.0
    try:
        snap = client.scrape()
        if decode and tokens_out[0]:
            # __metrics__ is republished once a second: right after the
            # last reply the snapshot may predate the final decode
            # steps, so wait out one publish period when it is behind
            gen = sum(v for k, v in snap.get("counters", {}).items()
                      if k.startswith("serving_tokens_generated_total"))
            if gen < tokens_out[0]:
                time.sleep(1.2)
                snap = client.scrape()
        h = [v for k, v in snap.get("histograms", {}).items()
             if k.startswith("serving_batch_fill")]
        n = sum(x["count"] for x in h)
        if n:
            batch_fill = round(sum(x["sum"] for x in h) / n, 4)
        counters = snap.get("counters", {})
        spec_proposed = sum(
            v for k, v in counters.items()
            if k.startswith("spec_tokens_proposed_total"))
        spec_accepted = sum(
            v for k, v in counters.items()
            if k.startswith("spec_tokens_accepted_total"))
        prefix_hit_scraped = sum(
            v for k, v in counters.items()
            if k.startswith("prefix_cache_hit_tokens_total"))
    except Exception:
        pass

    total = max(sum(statuses.values()), 1)
    dropped = statuses.get("dropped", 0)
    report = {
        "model": args.model,
        "requests": args.requests,
        "offered_qps": args.qps,
        "statuses": statuses,
        "latency_ms_p50": round(percentile(latencies, 0.50), 3),
        "latency_ms_p99": round(percentile(latencies, 0.99), 3),
        "queue_wait_ms_p50": round(
            percentile(phase_samples["queue_wait_ms"], 0.50), 3),
        "queue_wait_ms_p99": round(
            percentile(phase_samples["queue_wait_ms"], 0.99), 3),
        "execute_ms_p50": round(
            percentile(phase_samples["execute_ms"], 0.50), 3),
        "execute_ms_p99": round(
            percentile(phase_samples["execute_ms"], 0.99), 3),
        "wire_ms_p50": round(
            percentile(phase_samples["wire_ms"], 0.50), 3),
        "wire_ms_p99": round(
            percentile(phase_samples["wire_ms"], 0.99), 3),
        "achieved_qps": round(len(latencies) / wall_s, 2) if wall_s else 0.0,
        "batch_fill": batch_fill,
        "shed_rate": round(statuses.get("shed", 0) / total, 4),
        "dropped": dropped,
        "failovers": client.failovers,
        "client_shed_retries": client.shed_retries,
    }
    if versions:
        report["versions"] = versions
    if disagg_n[0]:
        report["role_phases"] = {
            "disagg_requests": disagg_n[0],
            "prefill": {
                "queue_wait_ms_p50": round(percentile(
                    role_phase["prefill_queue_wait_ms"], 0.50), 3),
                "queue_wait_ms_p99": round(percentile(
                    role_phase["prefill_queue_wait_ms"], 0.99), 3),
                "prefill_ms_p50": round(percentile(
                    role_phase["prefill_ms"], 0.50), 3),
                "prefill_ms_p99": round(percentile(
                    role_phase["prefill_ms"], 0.99), 3)},
            "xfer": {
                "xfer_ms_p50": round(percentile(
                    role_phase["xfer_ms"], 0.50), 3),
                "xfer_ms_p99": round(percentile(
                    role_phase["xfer_ms"], 0.99), 3)},
            "decode": {
                "queue_wait_ms_p50": round(percentile(
                    decode_phase["queue_wait_ms"], 0.50), 3),
                "queue_wait_ms_p99": round(percentile(
                    decode_phase["queue_wait_ms"], 0.99), 3),
                "execute_ms_p50": round(percentile(
                    decode_phase["execute_ms"], 0.50), 3),
                "execute_ms_p99": round(percentile(
                    decode_phase["execute_ms"], 0.99), 3)}}
    if tier_stats:
        report["tiers"] = {
            t: {"requests": ts["requests"], "ok": ts["ok"],
                "shed": ts["shed"],
                "latency_ms_p50": round(percentile(ts["lat"], 0.50), 3),
                "latency_ms_p99": round(percentile(ts["lat"], 0.99), 3),
                "server_ms_p50": round(percentile(ts["server"], 0.50), 3),
                "server_ms_p99": round(percentile(ts["server"], 0.99), 3)}
            for t, ts in sorted(tier_stats.items())}
    if decode:
        # outputs_sha256 fingerprints every (prompt -> tokens) pair so
        # two runs of the SAME seeded traffic can assert bitwise-equal
        # output (the speculative-vs-greedy parity check in run_ci.sh)
        digest = hashlib.sha256(
            json.dumps(sorted((list(p), t) for p, t in out_map.items()))
            .encode()).hexdigest()
        report.update({
            "decode_mode": spec.get("mode"),
            "max_new_tokens": args.max_new,
            "tokens_generated": tokens_out[0],
            "tokens_per_sec": round(tokens_out[0] / wall_s, 2)
            if wall_s else 0.0,
            "ttft_ms_p50": round(percentile(ttfts, 0.50), 3),
            "ttft_ms_p99": round(percentile(ttfts, 0.99), 3),
            "itl_ms_p50": round(percentile(itls, 0.50), 3),
            "itl_ms_p99": round(percentile(itls, 0.99), 3),
            "speculative_k": spec.get("speculative_k", 0),
            "spec_tokens_proposed": spec_proposed,
            "spec_tokens_accepted": spec_accepted,
            "spec_acceptance_rate": round(
                spec_accepted / spec_proposed, 4) if spec_proposed else None,
            # shared-prefix traffic + KV prefix-cache effectiveness:
            # hit rate is client-side exact (Σ cached_tokens from reply
            # phases / Σ prompt tokens — independent of scrape windows);
            # the scraped counter is the server-side cross-check
            "prefix_share": args.prefix_share,
            "prefix_tokens": args.prefix_tokens
            if args.prefix_share > 0 else 0,
            "prefix_cache_hit_rate": round(
                cached_toks[0] / prompt_toks[0], 4)
            if prompt_toks[0] else None,
            "prefix_cache_hit_tokens": prefix_hit_scraped,
            "outputs_sha256": digest,
            "outputs_distinct": len(out_map),
        })
        if resume_rows:
            # per-resumed-session re-prefill cost: the destination
            # replays (prompt_len + resumed_tokens - 1) positions of
            # which cached_tokens came from adopted/matched KV blocks
            reprefill = [r["prompt_len"] + r["resumed_tokens"] - 1
                         - r["cached_tokens"] for r in resume_rows]
            report["resume"] = {
                "resumed_requests": len(resume_rows),
                "resumed_tokens": sum(r["resumed_tokens"]
                                      for r in resume_rows),
                "reprefill_tokens_max": max(reprefill),
                "rows": resume_rows,
            }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report), flush=True)
    if args.assert_no_drops and dropped:
        print("FAIL: %d requests dropped" % dropped, file=sys.stderr)
        return 1
    if args.canary_assert:
        label, _, frac = args.canary_assert.partition(":")
        want = float(frac or 1.0)
        ok_total = sum(versions.values())
        got = versions.get(label, 0) / ok_total if ok_total else 0.0
        if got < want:
            print("FAIL: version %s served %.3f of ok traffic "
                  "(wanted >= %.3f); versions=%s"
                  % (label, got, want, versions), file=sys.stderr)
            return 1
        print("CANARY-ASSERT ok: %s served %.3f >= %.3f"
              % (label, got, want), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
