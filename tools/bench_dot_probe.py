"""Probe: isolated dot efficiency at BERT-base bs256/seq128 shapes.

In-program matmul-class fusions run at ~43% MXU; this measures each dot
shape alone (barrier-chained, host-fetch sync) to separate "XLA dots are
slow at these shapes" from "the fused epilogues/layouts slow them down".
"""


import jax
import jax.numpy as jnp
import numpy as np
import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from bench_util import timed as _time, tunnel_rtt as _rtt
from jax import lax

REP = 64


def dot_chain(a, b, rep, batched=False):
    def body(c, _):
        ab, cb = lax.optimization_barrier((a, c))
        if batched:
            y = jnp.einsum("bik,bkj->bij", ab, b)
        else:
            y = jnp.dot(ab, b)
        yb = lax.optimization_barrier(y)
        return yb.reshape(-1)[0].astype(jnp.float32) * 1e-9 + cb * 0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), None, length=rep)
    return (out,)


def main():
    rtt = _rtt()
    print(f"device: {jax.devices()[0]}  RTT {rtt*1e3:.1f} ms")
    key = jax.random.PRNGKey(0)
    cases = [
        ("qkv/proj [32768,768]x[768,768]", (32768, 768), (768, 768), False),
        ("ffn1 [32768,768]x[768,3072]", (32768, 768), (768, 3072), False),
        ("ffn2 [32768,3072]x[3072,768]", (32768, 3072), (3072, 768), False),
        ("wgrad [768,32768]x[32768,3072]", (768, 32768), (32768, 3072),
         False),
        ("head [4915,768]x[768,30522]", (4915, 768), (768, 30522), False),
        ("scores [3072,128,64]x[3072,64,128]", (3072, 128, 64),
         (3072, 64, 128), True),
    ]
    for name, sa, sb, batched in cases:
        a = jax.random.normal(key, sa, jnp.bfloat16)
        b = jax.random.normal(key, sb, jnp.bfloat16)
        if batched:
            fl = 2 * sa[0] * sa[1] * sa[2] * sb[2]
        else:
            fl = 2 * sa[0] * sa[1] * sb[1]
        t = _time(lambda a, b, bt=batched: dot_chain(a, b, REP, bt), a, b)
        dev = max(t - rtt, 1e-9) / REP
        print(f"{name:36s} {dev*1e3:7.3f} ms  {fl/dev/1e12:6.1f} TF/s")


if __name__ == "__main__":
    main()
