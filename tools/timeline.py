"""Chrome-trace timeline exporter CLI (reference tools/timeline.py:131 —
converts profiler output into chrome://tracing format).

Two sources:
  --profile_path  a profile dump written by fluid.profiler (the host
                  RecordEvent stream; already chrome-trace JSON here)
  --xplane_dir    a jax.profiler trace dir (plugins/profile/*/*.xplane.pb);
                  the device timeline is decoded with the in-repo proto
                  reader (no tensorboard needed) and emitted as chrome
                  trace events

Usage:
    python tools/timeline.py --profile_path prof.json --timeline_path out.json
    python tools/timeline.py --xplane_dir /tmp/trace --timeline_path out.json

Open chrome://tracing and load the output.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def track_meta(pid, name, tid=None, thread_name=None, sort_index=None):
    """Chrome-trace metadata events (ph "M") naming a process track and
    optionally one of its threads — shared with tools/trace_view.py."""
    evs = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}]
    if sort_index is not None:
        evs.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"sort_index": sort_index}})
    if tid is not None and thread_name is not None:
        evs.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": thread_name}})
    return evs


def from_profiler(profile_path):
    with open(profile_path) as f:
        data = json.load(f)
    # fluid.profiler already emits chrome-trace dicts ({"traceEvents": ...}
    # or a bare list)
    if isinstance(data, dict) and "traceEvents" in data:
        return data
    return {"traceEvents": data}


def from_xplane(xplane_dir):
    from paddle_tpu.proto_compat import _parse_fields, _first, _signed64

    paths = glob.glob(os.path.join(xplane_dir,
                                   "plugins/profile/*/*.xplane.pb"))
    if not paths:
        paths = glob.glob(os.path.join(xplane_dir, "*.xplane.pb"))
    if not paths:
        raise FileNotFoundError("no .xplane.pb under %s" % xplane_dir)
    events = []
    for path in paths:
        space = _parse_fields(open(path, "rb").read())
        for plane_buf in space.get(1, []):
            p = _parse_fields(plane_buf)
            pname = _first(p, 2, b"").decode()
            emeta = {}
            for entry in p.get(4, []):
                e = _parse_fields(entry)
                v = _parse_fields(_first(e, 2, b""))
                emeta[_signed64(_first(e, 1, 0))] = _first(
                    v, 2, b"").decode()
            for line_buf in p.get(3, []):
                l = _parse_fields(line_buf)
                lname = _first(l, 2, b"").decode()
                ts0 = _signed64(_first(l, 3, 0))  # ns
                for ev_buf in l.get(4, []):
                    ev = _parse_fields(ev_buf)
                    name = emeta.get(_signed64(_first(ev, 1, 0)), "?")
                    off_ps = _signed64(_first(ev, 2, 0))
                    dur_ps = _signed64(_first(ev, 3, 0))
                    events.append({
                        "name": name[:120],
                        "ph": "X",
                        "pid": pname,
                        "tid": lname,
                        "ts": (ts0 * 1000 + off_ps) / 1e6,  # us
                        "dur": dur_ps / 1e6,
                    })
    return {"traceEvents": events}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", default=None)
    ap.add_argument("--xplane_dir", default=None)
    ap.add_argument("--timeline_path", required=True)
    args = ap.parse_args(argv)
    if args.profile_path:
        trace = from_profiler(args.profile_path)
    elif args.xplane_dir:
        trace = from_xplane(args.xplane_dir)
    else:
        ap.error("need --profile_path or --xplane_dir")
    with open(args.timeline_path, "w") as f:
        json.dump(trace, f)
    print("wrote %d events to %s" % (len(trace["traceEvents"]),
                                     args.timeline_path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
