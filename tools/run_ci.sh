#!/usr/bin/env bash
# CI harness (reference paddle/scripts/paddle_build.sh analog): build the
# native pieces, run the full test pyramid, smoke the bench + graft entry.
# Usage: tools/run_ci.sh [quick|full|tpu|--layout-smoke|--obs-smoke|--lint|--elastic-smoke|--zero1-smoke|--cache-smoke|--kernel-smoke|--serve-smoke|--fleetmon-smoke|--trace-smoke|--decode-smoke|--disagg-smoke|--migrate-smoke|--ckpt-smoke]
set -euo pipefail
cd "$(dirname "$0")/.."

MODE="${1:-full}"

if [ "$MODE" = "--lint" ]; then
  # static-analysis leg: verifier unit tests, then proglint over every
  # bundled model (+ grad programs + a transpiled 2-pserver split) with
  # FLAGS_static_check=error — any error/warning diagnostic fails the leg
  echo "== lint: program verifier tests =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_program_verifier.py -q
  echo "== lint: proglint over bundled models (FLAGS_static_check=error) =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/proglint.py --grad --transpile 2
  echo "== lint: world verifier tests =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_world_verifier.py -q
  echo "== lint: whole-world checks (dp2 / dp4xtp2 / zero1) =="
  # every rank of each world is materialized and its collective schedule
  # lockstep-matched (DL101-DL104) + peak-HBM-estimated (MEM001-MEM003);
  # keep to the two fast zoo models so the leg stays O(seconds)
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/proglint.py --builtin mnist_mlp --builtin word2vec --world 2
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/proglint.py --builtin mnist_mlp --builtin word2vec \
    --world 8 --mesh 4x2
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/proglint.py --builtin mnist_mlp --builtin word2vec \
    --world 2 --zero1
  echo "== lint: concurrency lint tests (CC1xx) =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_threadlint.py -q
  echo "== lint: threadlint over paddle_tpu/ (must be clean mod waivers) =="
  JAX_PLATFORMS=cpu python tools/threadlint.py
  echo "== lint: threadlint seeded-defect self-test (must exit 1) =="
  # the planted CC101 inversion MUST be detected: exit 1 is the success
  # path here, anything else (0 = missed, 2 = misattributed) fails CI
  set +e
  JAX_PLATFORMS=cpu python tools/threadlint.py --seed-defect cc101
  seed_rc=$?
  set -e
  if [ "$seed_rc" -ne 1 ]; then
    echo "CI --lint: FAIL (seed-defect cc101 exit=$seed_rc, want 1)"
    exit 1
  fi
  echo "CI --lint: PASS"
  exit 0
fi

if [ "$MODE" = "--elastic-smoke" ]; then
  # elastic re-quorum leg: DL005 verifier units + the full 3-member
  # SIGKILL/evict/restore/rejoin subprocess scenario, everything under
  # FLAGS_static_check=error so any post-requorum rewrite that fails the
  # verifier kills the run instead of limping into XLA
  echo "== elastic smoke: DL005 + evict/rejoin subprocess scenario =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_dist_elastic_subprocess.py -q
  echo "CI --elastic-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--zero1-smoke" ]; then
  # ZeRO-1 + quantized-allreduce leg: the sharding/parity/DL006 unit
  # tests, then an 8-device dryrun of the sharded int8 path with the
  # static verifier in error mode (a stale shard table or drifted
  # dequant scale kills the run instead of limping into XLA)
  echo "== zero1 smoke: sharding + quantized allreduce tests =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_zero1_sharding.py -q
  echo "== zero1 smoke: 8-device int8 sharded dryrun =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error FLAGS_collective_mode=zero1 \
    FLAGS_allreduce_dtype=int8 python tools/zero1_smoke.py
  echo "CI --zero1-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--cache-smoke" ]; then
  # persistent-compilation-cache leg: the cache + standby unit/subprocess
  # tests, then a two-process reuse dryrun through the CLI — process 1
  # prewarms a bundled model, process 2 must restore it from disk (the
  # "disk" source assertion) — all under FLAGS_static_check=error
  echo "== cache smoke: compile cache + elastic standby tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_compile_cache.py \
    tests/test_elastic_standby.py -q
  echo "== cache smoke: two-process prewarm -> restore dryrun =="
  CC_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/compile_cache.py --dir "$CC_DIR" prewarm --model mnist_mlp
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python tools/compile_cache.py --dir "$CC_DIR" prewarm --model mnist_mlp \
    | grep -q " disk "
  python tools/compile_cache.py --dir "$CC_DIR" stats
  rm -rf "$CC_DIR"
  echo "CI --cache-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--kernel-smoke" ]; then
  # Pallas fused-block leg: interpret-mode parity tests for the three
  # kernel families (conv+bn+relu, fused optimizer, embedding-bag) plus
  # the adoption-funnel units, then one op_bench --pallas probe config
  # driven end-to-end through the real op registry in interpret mode
  # with the static verifier in error mode
  echo "== kernel smoke: Pallas block-kernel parity + adoption tests =="
  JAX_PLATFORMS=cpu PADDLE_PALLAS_INTERPRET=1 \
    python -m pytest tests/test_pallas_blocks.py -q
  echo "== kernel smoke: interpret-mode op_bench probe (embedding_bag) =="
  JAX_PLATFORMS=cpu PADDLE_PALLAS_INTERPRET=1 FLAGS_static_check=error \
    python tools/op_bench.py tools/probes/embedding_bag.json \
    --pallas --device cpu --repeat 2 --warmup 1
  echo "CI --kernel-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--serve-smoke" ]; then
  # continuous-batching serving leg: the engine/wire/clone unit tests,
  # then a live 2-replica fleet — prewarm both buckets AOT, stream 200
  # open-loop requests through the endpoints file while one replica is
  # SIGKILLed mid-stream — 0 dropped requests is the hard invariant, and
  # the scraped serving_* metrics must answer over the survivor
  echo "== serve smoke: serving + threaded-clone tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_serving.py \
    tests/test_serving_fleet_subprocess.py tests/test_inference.py -q
  echo "== serve smoke: 2-replica fleet + SIGKILL under load =="
  SRV_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-model "$SRV_DIR/model"
  SRV_ENV=(JAX_PLATFORMS=cpu FLAGS_static_check=error FLAGS_telemetry=1
           FLAGS_serving_hb_interval=0.2 FLAGS_serving_hb_timeout=1.5
           FLAGS_compile_cache_dir="$SRV_DIR/cc")
  env "${SRV_ENV[@]}" python tools/serve.py --model fc="$SRV_DIR/model" \
    --rank 0 --fleet 127.0.0.1:9460,127.0.0.1:9461 --buckets 1,4 \
    --endpoints-file "$SRV_DIR/eps.json" > "$SRV_DIR/r0.log" 2>&1 &
  R0=$!
  env "${SRV_ENV[@]}" python tools/serve.py --model fc="$SRV_DIR/model" \
    --rank 1 --fleet 127.0.0.1:9460,127.0.0.1:9461 --buckets 1,4 \
    --endpoints-file "$SRV_DIR/eps.json" > "$SRV_DIR/r1.log" 2>&1 &
  R1=$!
  trap 'kill -9 $R0 $R1 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$SRV_DIR/r0.log" && grep -q READY "$SRV_DIR/r1.log" \
      && break
    sleep 1
  done
  grep -q READY "$SRV_DIR/r0.log" && grep -q READY "$SRV_DIR/r1.log"
  # both buckets must be present in rank 0's prewarm manifest
  grep -q '"1"' "$SRV_DIR/r0.log" && grep -q '"4"' "$SRV_DIR/r0.log"
  ( sleep 2; kill -9 $R1 2>/dev/null || true ) &
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$SRV_DIR/eps.json" --model fc --requests 200 \
    --qps 50 --out "$SRV_DIR/BENCH_serving.json" --assert-no-drops
  # grep -c (not -q): -q's early exit SIGPIPEs the dump under pipefail
  python tools/metrics_dump.py --scrape 127.0.0.1:9460 --serving \
    | grep -c serving_batches_total > /dev/null
  kill $R0 2>/dev/null || true
  trap - EXIT

  echo "== serve smoke: SLO-tiered admission under overload =="
  # single replica, tiny queue, one 4-row bucket.  The armed delay fault
  # point (satellite: FLAGS_fault_spec on the execute path) makes every
  # batch take 50-150 ms, so qps 75 of one-row requests is a genuine
  # ~2x overload of the ~36/s capacity.  The 150 ms batch window makes
  # the paid-p99 bound meaningful: the uncontended baseline pays a full
  # coalescing window per solo request, and under overload a paid
  # arrival evicts queued free work and boards the NEXT dispatch, so
  # its wait is the in-flight remainder — bounded by that same window —
  # while free-tier traffic queues behind it and sheds
  env "${SRV_ENV[@]}" FLAGS_serving_max_queue=4 \
    FLAGS_serving_batch_window_ms=150 \
    FLAGS_fault_spec="serving.execute.fc:delay:1.0" \
    python tools/serve.py --model fc="$SRV_DIR/model" --port 9462 \
    --buckets 4 > "$SRV_DIR/tier.log" 2>&1 &
  R2=$!
  trap 'kill -9 $R2 2>/dev/null || true' EXIT
  for _ in $(seq 60); do grep -q READY "$SRV_DIR/tier.log" && break; sleep 1; done
  grep -q READY "$SRV_DIR/tier.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9462 \
    --model fc --requests 40 --qps 5 --batch-mix 1 --tier-mix paid:1.0 \
    --out "$SRV_DIR/BENCH_tier_base.json" --assert-no-drops
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9462 \
    --model fc --requests 240 --qps 75 --batch-mix 1 \
    --tier-mix paid:0.12,free:0.88 \
    --out "$SRV_DIR/BENCH_tier_overload.json"
  python tools/metrics_dump.py --scrape 127.0.0.1:9462 --serving \
    | grep -c serving_tier_shed_total > /dev/null
  kill -9 $R2 2>/dev/null || true
  trap - EXIT
  python - "$SRV_DIR/BENCH_tier_base.json" \
    "$SRV_DIR/BENCH_tier_overload.json" <<'EOF'
import json, sys
base = json.load(open(sys.argv[1]))["tiers"]["paid"]
over = json.load(open(sys.argv[2]))["tiers"]
paid, free = over["paid"], over["free"]
shed = paid["shed"] + free["shed"]
assert shed > 0, "overload run never shed — not actually overloaded"
frac_free = free["shed"] / shed
b, p = base["server_ms_p99"], paid["server_ms_p99"]
# 1.2x with a small absolute floor: at ms-scale baselines the in-flight
# batch alone exceeds 1.2x, so the bound is max(1.2x, +20ms)
bound = max(1.2 * b, b + 20.0)
print("TIER paid server p99 %.1f ms under overload (uncontended %.1f, "
      "bound %.1f); %d shed, %.0f%% free-tier"
      % (p, b, bound, shed, frac_free * 100))
assert paid["ok"] > 0, "no paid request survived overload"
assert p <= bound, "paid p99 %.1f ms blew the %.1f ms bound" % (p, bound)
assert frac_free >= 0.90, \
    "shed load only %.0f%% free-tier (< 90%%)" % (frac_free * 100)
EOF

  echo "== serve smoke: chaos canary flip (SIGKILL mid-flip under load) =="
  # 3 replicas serving fc AND fc@v2 (same weights, both prewarmed); a
  # 50% canary starts, then the flip lands while rank 1 is SIGKILLed
  # under open-loop load — 0 drops, and every survivor must converge on
  # the flipped version (the monitor's re-broadcast heals missed sends).
  # The metrics gate is parked (huge min_samples): the same-weights
  # canary must never spuriously roll back mid-chaos
  CHS_ENV=("${SRV_ENV[@]}" FLAGS_rollout_gate_min_samples=1000000)
  CFLEET=127.0.0.1:9463,127.0.0.1:9464,127.0.0.1:9465
  for r in 0 1 2; do
    env "${CHS_ENV[@]}" python tools/serve.py \
      --model fc="$SRV_DIR/model" --model fc@v2="$SRV_DIR/model" \
      --rank $r --fleet "$CFLEET" --buckets 1,4 \
      --endpoints-file "$SRV_DIR/ceps.json" > "$SRV_DIR/c$r.log" 2>&1 &
    eval "C$r=\$!"
  done
  trap 'kill -9 $C0 $C1 $C2 2>/dev/null || true' EXIT
  for _ in $(seq 90); do
    grep -q READY "$SRV_DIR/c0.log" && grep -q READY "$SRV_DIR/c1.log" \
      && grep -q READY "$SRV_DIR/c2.log" && break
    sleep 1
  done
  grep -q READY "$SRV_DIR/c2.log"
  JAX_PLATFORMS=cpu python - "$SRV_DIR/ceps.json" <<'EOF'
import sys
from paddle_tpu.serving import ServingClient
c = ServingClient(endpoints_file=sys.argv[1])
r = c.rollout({"op": "start", "model": "fc", "active": "fc",
               "canary": "fc@v2", "fraction": 0.5})
assert r.get("status") == "ok", r
print("canary started:", r["phases"]["routes"])
EOF
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$SRV_DIR/ceps.json" --model fc --requests 240 \
    --qps 60 --out "$SRV_DIR/BENCH_chaos_flip.json" --assert-no-drops &
  LG=$!
  sleep 1.5
  # the flip and the SIGKILL race each other mid-stream
  ( JAX_PLATFORMS=cpu python - "$SRV_DIR/ceps.json" <<'EOF'
import sys
from paddle_tpu.serving import ServingClient
r = ServingClient(endpoints_file=sys.argv[1]).rollout(
    {"op": "flip", "model": "fc"})
assert r.get("status") == "ok", r
print("flipped:", r["phases"]["routes"])
EOF
  ) &
  FLIP=$!
  kill -9 $C1 2>/dev/null || true
  wait $FLIP
  wait $LG   # 0 dropped requests through the kill + flip
  # every survivor must agree on the flipped version
  JAX_PLATFORMS=cpu python - <<'EOF'
import sys, time
from paddle_tpu.serving import ServingClient
c = ServingClient(endpoints=["127.0.0.1:9463", "127.0.0.1:9465"])
deadline = time.time() + 30
while True:
    docs = [c.rollout_state(ep) for ep in ("127.0.0.1:9463",
                                           "127.0.0.1:9465")]
    routes = [d.get("models", {}).get("fc") for d in docs]
    if all(r and r["state"] == "flipped" and r["active"] == "fc@v2"
           for r in routes):
        print("survivors agree: fc -> fc@v2 (flipped) on both replicas")
        break
    if time.time() > deadline:
        sys.exit("survivors never converged: %s" % routes)
    time.sleep(0.3)
EOF
  # post-flip traffic must be served ~entirely by fc@v2
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$SRV_DIR/ceps.json" --model fc --requests 80 \
    --qps 80 --out "$SRV_DIR/BENCH_postflip.json" --assert-no-drops \
    --canary-assert fc@v2:0.99
  kill -9 $C0 $C2 2>/dev/null || true
  trap - EXIT

  echo "== serve smoke: canary rollback gate (seeded bad v2) =="
  # single replica; every fc@v2 execution raises via the armed fault
  # point, so the canary's error rate trips the gate and the monitor
  # rolls back on its own.  GATE-VERDICT printed beside the BENCH rows
  # is the BASELINE.md round-16 validity requirement
  env "${SRV_ENV[@]}" FLAGS_rollout_gate_min_samples=5 \
    FLAGS_fault_spec="serving.execute.fc@v2:error:1.0" \
    python tools/serve.py --model fc="$SRV_DIR/model" \
    --model fc@v2="$SRV_DIR/model" --port 9466 --buckets 1,4 \
    > "$SRV_DIR/gate.log" 2>&1 &
  R6=$!
  trap 'kill -9 $R6 2>/dev/null || true' EXIT
  for _ in $(seq 60); do grep -q READY "$SRV_DIR/gate.log" && break; sleep 1; done
  grep -q READY "$SRV_DIR/gate.log"
  JAX_PLATFORMS=cpu python - <<'EOF'
from paddle_tpu.serving import ServingClient
c = ServingClient(endpoints=["127.0.0.1:9466"])
r = c.rollout({"op": "start", "model": "fc", "active": "fc",
               "canary": "fc@v2", "fraction": 0.5})
assert r.get("status") == "ok", r
EOF
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9466 \
    --model fc --requests 60 --qps 60 \
    --out "$SRV_DIR/BENCH_rollback.json"
  JAX_PLATFORMS=cpu python - <<'EOF'
import sys, time
from paddle_tpu.serving import ServingClient
c = ServingClient(endpoints=["127.0.0.1:9466"])
deadline = time.time() + 30
while True:
    doc = c.rollout_state("127.0.0.1:9466").get("models", {}).get("fc")
    if doc and doc["state"] == "rolled_back":
        break
    if time.time() > deadline:
        sys.exit("gate never rolled the canary back: %s" % doc)
    time.sleep(0.3)
st = c.rollout({"op": "status"})
gate = st["phases"]["gates"].get("fc", {})
print("GATE-VERDICT model=fc verdict=%s reason=%r (state=rolled_back)"
      % (gate.get("verdict"), gate.get("reason")))
assert gate.get("verdict") == "trip", gate
EOF
  python tools/metrics_dump.py --scrape 127.0.0.1:9466 --serving \
    | grep -c rollout_rollbacks_total > /dev/null
  kill -9 $R6 2>/dev/null || true
  trap - EXIT

  echo "== serve smoke: autoscaler (prewarmed standby up, drain down) =="
  # rank 0 alone holds a 2-slot fleet; sustained overload must fork the
  # prewarmed standby into slot 1 (endpoints file grows), sustained idle
  # must drain + retire it (file shrinks) — hysteresis ticks shortened
  # for CI wall time
  env "${SRV_ENV[@]}" FLAGS_serving_max_queue=4 \
    FLAGS_serving_autoscale_interval=0.25 FLAGS_serving_scale_up_ticks=2 \
    FLAGS_serving_scale_down_ticks=4 FLAGS_serving_autoscale_cooldown=4 \
    python tools/serve.py --model fc="$SRV_DIR/model" --rank 0 \
    --fleet 127.0.0.1:9467,127.0.0.1:9468 --buckets 1 \
    --endpoints-file "$SRV_DIR/aeps.json" --autoscale --max-replicas 2 \
    > "$SRV_DIR/a0.log" 2>&1 &
  A0=$!
  trap 'kill -9 $A0 2>/dev/null || true; pkill -9 -f "127.0.0.1:9467,127.0.0.1:9468" 2>/dev/null || true' EXIT
  for _ in $(seq 60); do grep -q READY "$SRV_DIR/a0.log" && break; sleep 1; done
  grep -q READY "$SRV_DIR/a0.log"
  # wait out the eviction of the never-started slot 1 (live must be [0])
  python - "$SRV_DIR/aeps.json" <<'EOF'
import json, sys, time
deadline = time.time() + 30
while time.time() < deadline:
    try:
        if len(json.load(open(sys.argv[1]))["endpoints"]) == 1:
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("fleet never settled to 1 live replica")
EOF
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9467 \
    --model fc --requests 800 --qps 500 --batch-mix 1 \
    --out "$SRV_DIR/BENCH_autoscale.json" &
  ALG=$!
  # sustained pressure -> standby forked into slot 1 (cold start is
  # restore-dominated via the shared compile cache)
  python - "$SRV_DIR/aeps.json" <<'EOF'
import json, sys, time
deadline = time.time() + 90
while time.time() < deadline:
    try:
        if len(json.load(open(sys.argv[1]))["endpoints"]) == 2:
            print("scaled UP to 2 replicas")
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("autoscaler never scaled up under overload")
EOF
  wait $ALG || true
  # sustained idle -> the standby drains at a batch boundary and retires
  python - "$SRV_DIR/aeps.json" <<'EOF'
import json, sys, time
deadline = time.time() + 90
while time.time() < deadline:
    try:
        if len(json.load(open(sys.argv[1]))["endpoints"]) == 1:
            print("scaled DOWN to 1 replica")
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("autoscaler never retired the idle standby")
EOF
  python - <<'EOF'
from paddle_tpu.core import telemetry
snap = telemetry.scrape("127.0.0.1:9467")
c = snap.get("counters", {})
up = c.get("autoscale_events_total{dir=up}", 0)
down = c.get("autoscale_events_total{dir=down}", 0)
assert up >= 1 and down >= 1, \
    "autoscale_events_total up=%s down=%s" % (up, down)
print("autoscale_events_total: up=%d down=%d" % (up, down))
EOF
  kill -9 $A0 2>/dev/null || true
  pkill -9 -f "127.0.0.1:9467,127.0.0.1:9468" 2>/dev/null || true
  trap - EXIT
  rm -rf "$SRV_DIR"
  echo "CI --serve-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--fleetmon-smoke" ]; then
  # fleet observability leg (PR 18): the mergeable-histogram / windowed-
  # rate / burn-alert unit tests plus the live fleet_top schema test,
  # then a 2-replica fleet where rank 1 carries an injected ~100ms
  # execute delay — the coordinator's FleetMonitor must publish a
  # fleet-merged server_ms p99 that REFLECTS the slow replica (the
  # healthy replica's local p99 stays fast), the multi-window burn-rate
  # alert must FIRE under the seeded Poisson load and CLEAR after the
  # fault window drains, and a trimmed PR-16 autoscale pass must still
  # scale 1->2 with pressure now sourced from the monitor's windowed
  # fleet rates
  echo "== fleetmon smoke: metrics plane + live fleet_top tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_fleetmon.py \
    tests/test_fleetmon_subprocess.py -q
  echo "== fleetmon smoke: 2-replica fleet, one slow replica =="
  FM_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-model "$FM_DIR/model"
  FM_ENV=(JAX_PLATFORMS=cpu FLAGS_static_check=error FLAGS_telemetry=1
          FLAGS_serving_hb_interval=0.2 FLAGS_serving_hb_timeout=1.5
          FLAGS_serving_fleetmon_interval=0.5
          FLAGS_serving_rate_window=10
          FLAGS_serving_slo_fast_window=6
          FLAGS_serving_slo_slow_window=15
          FLAGS_serving_slo_rules="srv:server_ms:p99:60"
          FLAGS_compile_cache_dir="$FM_DIR/cc")
  env "${FM_ENV[@]}" python tools/serve.py --model fc="$FM_DIR/model" \
    --rank 0 --fleet 127.0.0.1:9470,127.0.0.1:9471 --buckets 1,4 \
    --endpoints-file "$FM_DIR/eps.json" > "$FM_DIR/f0.log" 2>&1 &
  F0=$!
  env "${FM_ENV[@]}" FLAGS_fault_spec="serving.execute.fc:delay:1.0" \
    python tools/serve.py --model fc="$FM_DIR/model" \
    --rank 1 --fleet 127.0.0.1:9470,127.0.0.1:9471 --buckets 1,4 \
    --endpoints-file "$FM_DIR/eps.json" > "$FM_DIR/f1.log" 2>&1 &
  F1=$!
  trap 'kill -9 $F0 $F1 2>/dev/null || true; pkill -9 -f "127.0.0.1:9470,127.0.0.1:9471" 2>/dev/null || true' EXIT
  for _ in $(seq 90); do
    grep -q READY "$FM_DIR/f0.log" && grep -q READY "$FM_DIR/f1.log" \
      && break
    sleep 1
  done
  grep -q READY "$FM_DIR/f0.log" && grep -q READY "$FM_DIR/f1.log"
  # seeded Poisson load, half landing on the delayed replica; runs in
  # the background while the monitor's windows fill
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$FM_DIR/eps.json" --model fc --requests 300 \
    --qps 40 --seed 7 --deadline-ms 5000 --batch-mix 1 \
    --out "$FM_DIR/BENCH_fleetmon.json" &
  FLG=$!
  # the merged p99 must reflect the slow replica while the healthy
  # replica's own row stays fast, and the burn alert must fire
  python - <<'EOF'
import sys, time
from paddle_tpu.core import telemetry
deadline = time.time() + 60
fired = reflected = False
while time.time() < deadline and not (fired and reflected):
    try:
        doc = telemetry.scrape("127.0.0.1:9470", timeout=3.0,
                               key="__fleet__")
    except Exception:
        time.sleep(0.5)
        continue
    merged = [h for k, h in doc["histograms"].items()
              if k.split("{", 1)[0] == "server_ms"]
    if merged and max(h["p99"] for h in merged) >= 60.0:
        rows = {r["endpoint"]: r for r in doc["replicas"]}
        fast = rows.get("127.0.0.1:9470", {}).get("p99_ms", {})
        if fast.get("server_ms", 1e9) < max(h["p99"] for h in merged):
            reflected = True
    if any(s["active"] for s in doc.get("slo", [])):
        fired = True
    time.sleep(0.5)
if not reflected:
    sys.exit("fleet-merged p99 never reflected the slow replica")
if not fired:
    sys.exit("burn-rate alert never fired under the injected delay")
print("fleet p99 reflects slow replica; SLO alert FIRED")
EOF
  wait $FLG
  # load is over: the fast window drains and the alert must clear
  python - <<'EOF'
import sys, time
from paddle_tpu.core import telemetry
deadline = time.time() + 60
while time.time() < deadline:
    try:
        doc = telemetry.scrape("127.0.0.1:9470", timeout=3.0,
                               key="__fleet__")
        snap = telemetry.scrape("127.0.0.1:9470", timeout=3.0)
    except Exception:
        time.sleep(0.5)
        continue
    c = snap.get("counters", {})
    fires = sum(v for k, v in c.items()
                if k.startswith("slo_alerts_total{event=fire"))
    clears = sum(v for k, v in c.items()
                 if k.startswith("slo_alerts_total{event=clear"))
    # the __metrics__ snapshot republishes on its own 1s cadence, so
    # the clear counter can lag the doc's active flag by one tick —
    # wait for BOTH
    if not any(s["active"] for s in doc.get("slo", [])) \
            and fires >= 1 and clears >= 1:
        print("SLO alert CLEARED (fires=%d clears=%d)"
              % (fires, clears))
        sys.exit(0)
    time.sleep(0.5)
sys.exit("burn-rate alert never cleared after the fault window")
EOF
  # operator surface against the live fleet: fleet_top --once --json
  # must emit the full schema, goodput included
  env "${FM_ENV[@]}" python tools/fleet_top.py --scrape 127.0.0.1:9470 \
    --once --json > "$FM_DIR/fleet_top.json"
  python - "$FM_DIR/fleet_top.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
need = {"t", "replicas", "replicas_up", "histograms", "counters",
        "rates", "goodput", "slo", "bucket_bounds"}
missing = need - set(doc)
assert not missing, "fleet_top doc missing %s" % missing
assert doc["replicas_up"] == 2, doc["replicas_up"]
assert doc["goodput"]["raw_replies_per_s"] >= 0.0
print("fleet_top schema OK: %d replicas, %d merged histograms"
      % (len(doc["replicas"]), len(doc["histograms"])))
EOF
  kill -9 $F0 $F1 2>/dev/null || true
  pkill -9 -f "127.0.0.1:9470,127.0.0.1:9471" 2>/dev/null || true
  trap - EXIT

  echo "== fleetmon smoke: autoscale 1->2 from windowed fleet rates =="
  # trimmed PR-16 leg on fresh ports: with the FleetMonitor running,
  # the coordinator's AutoScaler reads autoscale_metrics() (fleet
  # queue depth + windowed shed/s) instead of local instants — the
  # standby must still fork into slot 1 under sustained overload
  env "${FM_ENV[@]}" FLAGS_serving_max_queue=4 \
    FLAGS_serving_autoscale_interval=0.25 FLAGS_serving_scale_up_ticks=2 \
    FLAGS_serving_scale_down_ticks=4 FLAGS_serving_autoscale_cooldown=4 \
    python tools/serve.py --model fc="$FM_DIR/model" --rank 0 \
    --fleet 127.0.0.1:9477,127.0.0.1:9478 --buckets 1 \
    --endpoints-file "$FM_DIR/aeps.json" --autoscale --max-replicas 2 \
    > "$FM_DIR/a0.log" 2>&1 &
  FA0=$!
  trap 'kill -9 $FA0 2>/dev/null || true; pkill -9 -f "127.0.0.1:9477,127.0.0.1:9478" 2>/dev/null || true' EXIT
  for _ in $(seq 60); do grep -q READY "$FM_DIR/a0.log" && break; sleep 1; done
  grep -q READY "$FM_DIR/a0.log"
  python - "$FM_DIR/aeps.json" <<'EOF'
import json, sys, time
deadline = time.time() + 30
while time.time() < deadline:
    try:
        if len(json.load(open(sys.argv[1]))["endpoints"]) == 1:
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("fleet never settled to 1 live replica")
EOF
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9477 \
    --model fc --requests 800 --qps 500 --batch-mix 1 --seed 7 \
    --out "$FM_DIR/BENCH_fm_autoscale.json" &
  FALG=$!
  python - "$FM_DIR/aeps.json" <<'EOF'
import json, sys, time
deadline = time.time() + 90
while time.time() < deadline:
    try:
        if len(json.load(open(sys.argv[1]))["endpoints"]) == 2:
            print("scaled UP to 2 replicas")
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("autoscaler never scaled up under overload")
EOF
  wait $FALG || true
  # the monitor was live (fleet_replicas_up published) and the scale-up
  # event fired — the unit tests pin that the pressure values came from
  # autoscale_metrics()'s windowed view
  python - <<'EOF'
from paddle_tpu.core import telemetry
snap = telemetry.scrape("127.0.0.1:9477")
up = snap.get("counters", {}).get("autoscale_events_total{dir=up}", 0)
assert up >= 1, "autoscale_events_total{dir=up}=%s" % up
assert snap.get("gauges", {}).get("fleet_replicas_up", 0) >= 1, \
    "FleetMonitor never ticked on the coordinator"
print("autoscale up=%d with fleet_replicas_up=%g" % (
    up, snap["gauges"]["fleet_replicas_up"]))
EOF
  kill -9 $FA0 2>/dev/null || true
  pkill -9 -f "127.0.0.1:9477,127.0.0.1:9478" 2>/dev/null || true
  trap - EXIT
  rm -rf "$FM_DIR"
  echo "CI --fleetmon-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--decode-smoke" ]; then
  # autoregressive decode leg: paged-KV allocator + decode engine units,
  # then a live replica serving token-level continuous batching under a
  # mixed-length burst — zero runtime compiles after the bucket prewarm
  # is the hard invariant (flat executor_cache_miss_total), and the same
  # traffic against a request-level replica must be >=1.5x slower in
  # generated tokens/sec (the continuous-batching win); a third replica
  # with --speculative-k 3 replays the identical seeded traffic and must
  # produce bitwise-equal outputs (outputs_sha256) with its own flat
  # miss count (buckets x 3 speculative stepfn kinds); a prefix leg then
  # replays seeded shared-prefix traffic (--prefix-share 0.75) against a
  # cache-on and a cache-off replica — bitwise-equal outputs_sha256 is
  # the parity gate, hit rate >= 0.5 and a flat miss count prove the hit
  # path reuses blocks without compiling; a final leg reruns the token
  # traffic under FLAGS_decode_prefill_token_budget and must stay
  # bitwise-identical (budgeted prefill is scheduling only)
  echo "== decode smoke: paged KV cache + decode serving tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_kv_cache.py tests/test_decode_serving.py \
    tests/test_decode_fleet_subprocess.py -q
  echo "== decode smoke: token-level replica under mixed-length burst =="
  DEC_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-decoder "$DEC_DIR/dec"
  DEC_ENV=(JAX_PLATFORMS=cpu FLAGS_telemetry=1
           FLAGS_kv_block_size=8 FLAGS_kv_cache_blocks=64
           FLAGS_compile_cache_dir="$DEC_DIR/cc")
  env "${DEC_ENV[@]}" python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9480 --decode-buckets 4,8 --decode-mode token \
    > "$DEC_DIR/token.log" 2>&1 &
  D0=$!
  trap 'kill -9 $D0 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/token.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/token.log"
  # near-simultaneous arrivals (open-loop qps >> service rate) so the
  # scheduler, not the arrival schedule, is the bottleneck; high prompt
  # length variance is what request-level batching wastes lanes on
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9480 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,24 --max-new 8 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_token.json" --assert-no-drops
  # zero runtime XLA compiles under mixed-length decode: the miss
  # counter must still equal the 2 prewarmed lane buckets
  python - <<'EOF'
from paddle_tpu.core import telemetry
snap = telemetry.scrape("127.0.0.1:9480")
miss = sum(v for k, v in snap["counters"].items()
           if k.startswith("executor_cache_miss_total"))
steps = sum(v for k, v in snap["counters"].items()
            if k.startswith("serving_decode_steps_total"))
assert steps > 0, "no decode steps recorded"
assert miss == 2, "runtime compiles under decode: miss=%s != 2" % miss
print("flat executor_cache_miss_total OK: %d over %d decode steps"
      % (miss, steps))
EOF
  python tools/metrics_dump.py --scrape 127.0.0.1:9480 --decode \
    | grep -c kv_blocks_in_use > /dev/null
  python tools/metrics_dump.py --scrape 127.0.0.1:9480 --decode \
    | grep -c decode_batch_occupancy > /dev/null
  kill -9 $D0 2>/dev/null || true
  echo "== decode smoke: request-level baseline, same traffic =="
  env "${DEC_ENV[@]}" python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9481 --decode-buckets 4,8 --decode-mode request \
    > "$DEC_DIR/request.log" 2>&1 &
  D1=$!
  trap 'kill -9 $D1 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/request.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/request.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9481 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,24 --max-new 8 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_request.json" --assert-no-drops
  kill -9 $D1 2>/dev/null || true
  trap - EXIT
  python - "$DEC_DIR/BENCH_decode_token.json" \
    "$DEC_DIR/BENCH_decode_request.json" <<'EOF'
import json, sys
tok = json.load(open(sys.argv[1]))
req = json.load(open(sys.argv[2]))
rt, rr = tok["tokens_per_sec"], req["tokens_per_sec"]
ratio = rt / max(rr, 1e-9)
print("token-level %.1f tok/s vs request-level %.1f tok/s -> %.2fx"
      % (rt, rr, ratio))
print("token-level TTFT p50/p99 = %s/%s ms, ITL p50/p99 = %s/%s ms"
      % (tok["ttft_ms_p50"], tok["ttft_ms_p99"],
         tok["itl_ms_p50"], tok["itl_ms_p99"]))
assert tok["ttft_ms_p50"] > 0, "no TTFT samples"
assert ratio >= 1.5, "continuous-batching win %.2fx < 1.5x" % ratio
EOF
  echo "== decode smoke: speculative decoding, same traffic =="
  # third replica: same bundle (save_demo_decoder ships a draft), same
  # seeded traffic, FLAGS_speculative_k=3 — greedy accept-longest-prefix
  # must be BITWISE identical to the non-speculative token run
  # (outputs_sha256), and the miss counter must stay flat at
  # 2 buckets x 3 stepfn kinds (verify + draft rollout + draft ingest)
  env "${DEC_ENV[@]}" python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9482 --decode-buckets 4,8 --decode-mode token \
    --speculative-k 3 > "$DEC_DIR/spec.log" 2>&1 &
  D2=$!
  trap 'kill -9 $D2 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/spec.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/spec.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9482 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,24 --max-new 8 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_spec.json" --assert-no-drops
  python - <<'EOF'
from paddle_tpu.core import telemetry
snap = telemetry.scrape("127.0.0.1:9482")
miss = sum(v for k, v in snap["counters"].items()
           if k.startswith("executor_cache_miss_total"))
assert miss == 6, \
    "runtime compiles under speculation: miss=%s != 2 buckets x 3" % miss
print("flat executor_cache_miss_total OK under speculation: %d" % miss)
EOF
  python tools/metrics_dump.py --scrape 127.0.0.1:9482 --decode \
    | grep -c spec_tokens_proposed_total > /dev/null
  kill -9 $D2 2>/dev/null || true
  trap - EXIT
  python - "$DEC_DIR/BENCH_decode_spec.json" \
    "$DEC_DIR/BENCH_decode_token.json" <<'EOF'
import json, sys
spec = json.load(open(sys.argv[1]))
base = json.load(open(sys.argv[2]))
assert spec["speculative_k"] == 3, spec["speculative_k"]
assert spec["outputs_sha256"] == base["outputs_sha256"], \
    "speculative outputs differ from greedy baseline: %s != %s" \
    % (spec["outputs_sha256"], base["outputs_sha256"])
assert spec["spec_tokens_proposed"] > 0, "speculation never ran"
acc = spec["spec_acceptance_rate"]
assert acc is not None and 0.0 < acc <= 1.0, acc
rs, rb = spec["tokens_per_sec"], base["tokens_per_sec"]
ratio = rs / max(rb, 1e-9)
print("speculative %.1f tok/s vs greedy %.1f tok/s -> %.2fx "
      "(acceptance %.0f%%)" % (rs, rb, ratio, acc * 100))
print("bitwise-equal outputs OK (%d distinct prompts)"
      % spec["outputs_distinct"])
if ratio < 1.3:
    # the 1-layer toy draft on a loaded CI box can miss the perf bar
    # even with high acceptance; parity + flat-miss asserted above are
    # the correctness gates, so the throughput bar alone degrades to a
    # loud notice instead of a hard failure
    print("SKIP-NOTICE: speculative speedup %.2fx < 1.3x target "
          "(acceptance %.0f%%) — correctness gates passed"
          % (ratio, acc * 100))
EOF
  echo "== decode smoke: prefix caching, cache-on vs cache-off =="
  # two replicas, identical seeded shared-prefix traffic (75% of
  # requests open with one of two 24-token prefixes = 3 full blocks at
  # FLAGS_kv_block_size=8): the cache-on replica must emit bitwise the
  # same streams as the cache-off one while skipping cached prefill
  # work.  Pool sized so the WHOLE burst's promised prompt blocks fit
  # (48 x 5 <= 255): admission sheds would complete different request
  # sets on the two replicas and void the sha comparison
  env "${DEC_ENV[@]}" FLAGS_prefix_cache=1 FLAGS_kv_cache_blocks=256 \
    python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9483 --decode-buckets 4,8 --decode-mode token \
    > "$DEC_DIR/prefix_on.log" 2>&1 &
  D3=$!
  trap 'kill -9 $D3 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/prefix_on.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/prefix_on.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9483 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,8 --max-new 8 \
    --prefix-share 0.75 --prefix-tokens 24 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_prefix_on.json" --assert-no-drops
  # the hit path feeds from mid-prompt through the SAME prewarmed
  # executables: the miss counter must still equal the 2 lane buckets
  python - <<'EOF'
from paddle_tpu.core import telemetry
snap = telemetry.scrape("127.0.0.1:9483")
miss = sum(v for k, v in snap["counters"].items()
           if k.startswith("executor_cache_miss_total"))
hits = sum(v for k, v in snap["counters"].items()
           if k.startswith("prefix_cache_hit_tokens_total"))
assert hits > 0, "prefix cache never hit under 0.75 shared-prefix traffic"
assert miss == 2, "runtime compiles on the hit path: miss=%s != 2" % miss
print("flat executor_cache_miss_total OK with %d prefix-cached tokens"
      % hits)
EOF
  python tools/metrics_dump.py --scrape 127.0.0.1:9483 --decode \
    | grep -c prefix_cache_hit_tokens_total > /dev/null
  kill -9 $D3 2>/dev/null || true
  env "${DEC_ENV[@]}" FLAGS_prefix_cache=0 FLAGS_kv_cache_blocks=256 \
    python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9484 --decode-buckets 4,8 --decode-mode token \
    > "$DEC_DIR/prefix_off.log" 2>&1 &
  D4=$!
  trap 'kill -9 $D4 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/prefix_off.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/prefix_off.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9484 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,8 --max-new 8 \
    --prefix-share 0.75 --prefix-tokens 24 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_prefix_off.json" --assert-no-drops
  kill -9 $D4 2>/dev/null || true
  trap - EXIT
  python - "$DEC_DIR/BENCH_decode_prefix_on.json" \
    "$DEC_DIR/BENCH_decode_prefix_off.json" <<'EOF'
import json, sys
on = json.load(open(sys.argv[1]))
off = json.load(open(sys.argv[2]))
assert on["outputs_sha256"] == off["outputs_sha256"], \
    "prefix-cached outputs differ from cache-off baseline: %s != %s" \
    % (on["outputs_sha256"], off["outputs_sha256"])
assert on["prefix_cache_hit_tokens"] > 0, "no prefix-cache hits scraped"
assert on["prefix_cache_hit_rate"] >= 0.5, \
    "hit rate %.2f < 0.5 at 0.75 prefix share" % on["prefix_cache_hit_rate"]
assert off["prefix_cache_hit_tokens"] == 0
assert off["prefix_cache_hit_rate"] == 0.0
rt_on, rt_off = on["ttft_ms_p50"], off["ttft_ms_p50"]
ratio = rt_off / max(rt_on, 1e-9)
print("prefix cache: hit rate %.0f%%, %d cached tokens, TTFT p50 "
      "%.1f ms (on) vs %.1f ms (off) -> %.2fx"
      % (on["prefix_cache_hit_rate"] * 100, on["prefix_cache_hit_tokens"],
         rt_on, rt_off, ratio))
print("bitwise-equal outputs OK (%d distinct prompts)"
      % on["outputs_distinct"])
if ratio < 1.3:
    # parity + hit rate + flat miss are the correctness gates; the TTFT
    # bar on a loaded CI box degrades to a loud notice, the real capture
    # lives in BASELINE.md round 15
    print("SKIP-NOTICE: prefix-cache TTFT win %.2fx < 1.3x target — "
          "correctness gates passed" % ratio)
EOF
  echo "== decode smoke: token-budget chunked prefill, same traffic =="
  # the token leg's exact seeded traffic replayed with an 8-token/iter
  # prefill budget: chunked admission may only change scheduling, never
  # tokens — outputs_sha256 must match BENCH_decode_token.json.  The
  # bigger pool keeps the slower queue drain from shedding (a shed
  # would change the completed set, not the tokens)
  env "${DEC_ENV[@]}" FLAGS_decode_prefill_token_budget=8 \
    FLAGS_kv_cache_blocks=256 \
    python tools/serve.py --model dec="$DEC_DIR/dec" \
    --port 9485 --decode-buckets 4,8 --decode-mode token \
    > "$DEC_DIR/budget.log" 2>&1 &
  D5=$!
  trap 'kill -9 $D5 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$DEC_DIR/budget.log" && break; sleep 1
  done
  grep -q READY "$DEC_DIR/budget.log"
  JAX_PLATFORMS=cpu python tools/loadgen.py --endpoints 127.0.0.1:9485 \
    --model dec --requests 48 --qps 400 --prompt-mix 2,4,24 --max-new 8 \
    --deadline-ms 30000 --retry-shed 4 \
    --out "$DEC_DIR/BENCH_decode_budget.json" --assert-no-drops
  kill -9 $D5 2>/dev/null || true
  trap - EXIT
  python - "$DEC_DIR/BENCH_decode_budget.json" \
    "$DEC_DIR/BENCH_decode_token.json" <<'EOF'
import json, sys
bud = json.load(open(sys.argv[1]))
base = json.load(open(sys.argv[2]))
assert bud["outputs_sha256"] == base["outputs_sha256"], \
    "budgeted outputs differ from unbudgeted baseline: %s != %s" \
    % (bud["outputs_sha256"], base["outputs_sha256"])
ri_b, ri_u = bud["itl_ms_p99"], base["itl_ms_p99"]
ratio = ri_b / max(ri_u, 1e-9)
print("budgeted ITL p99 %.1f ms vs unbudgeted %.1f ms -> %.2fx"
      % (ri_b, ri_u, ratio))
if ratio > 0.7:
    # decode-lane tail protection is the point of the budget, but the
    # ratio on a loaded CI box is noisy — parity above is the hard gate
    print("SKIP-NOTICE: budgeted ITL p99 ratio %.2fx > 0.7x target — "
          "parity gate passed" % ratio)
EOF
  rm -rf "$DEC_DIR"
  echo "CI --decode-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--disagg-smoke" ]; then
  # disaggregated prefill/decode leg: the __kvxfer__ codec / handoff /
  # reconciliation unit tests, then a 2-prefill+2-decode fleet replaying
  # the decode leg's round-15 mixed burst against a 4-monolith twin —
  # bitwise-equal outputs_sha256 is the hard gate, the per-role phase
  # p99s print beside it (TTFT/ITL p99 over ~1.10x the monolith twin
  # degrades to a loud SKIP-NOTICE on a loaded CI box); then a prefill
  # replica is SIGKILLed mid-transfer under load (zero admitted requests
  # dropped; the victim's flight recorder must name the in-flight
  # transfer frames); finally compact 1-prefill+1-decode pairs move the
  # same long-prompt traffic in f32 and int8 residency — the int8 pair
  # must be output-equal to an int8 monolith while moving <= 0.55x the
  # f32 pair's scraped kv_xfer_bytes_total
  echo "== disagg smoke: kvxfer codec + handoff + reconciliation tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_disagg_serving.py -q
  echo "== disagg smoke: 2-prefill+2-decode vs 4-monolith, same burst =="
  DSG_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-decoder "$DSG_DIR/dec"
  DSG_ENV=(JAX_PLATFORMS=cpu FLAGS_telemetry=1
           FLAGS_kv_block_size=8 FLAGS_kv_cache_blocks=256
           FLAGS_serving_hb_interval=0.2 FLAGS_serving_hb_timeout=1.5
           FLAGS_compile_cache_dir="$DSG_DIR/cc")
  # wait for the coordinator to publish the fleet's endpoints file —
  # clients learn the role column from THIS file, so traffic fired
  # before it lands would treat a handing-off prefill as a monolith
  dsg_wait_eps() {
    python - "$1" "$2" "$3" <<'EOF'
import json, sys, time
path, want_n, roles_csv = sys.argv[1], int(sys.argv[2]), sys.argv[3]
want_roles = [r for r in roles_csv.split(",") if r] or None
deadline = time.time() + 30
while time.time() < deadline:
    try:
        doc = json.load(open(path))
        if len(doc.get("endpoints", [])) == want_n and \
                (want_roles is None or doc.get("roles") == want_roles):
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("%s never published %d endpoints (roles=%s)"
         % (path, want_n, roles_csv or None))
EOF
  }
  MFLEET=127.0.0.1:9420,127.0.0.1:9421,127.0.0.1:9422,127.0.0.1:9423
  for r in 0 1 2 3; do
    env "${DSG_ENV[@]}" python tools/serve.py --model dec="$DSG_DIR/dec" \
      --rank $r --fleet "$MFLEET" --decode-buckets 4,8 \
      --decode-mode token --endpoints-file "$DSG_DIR/meps.json" \
      > "$DSG_DIR/m$r.log" 2>&1 &
    eval "M$r=\$!"
  done
  trap 'kill -9 $M0 $M1 $M2 $M3 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$DSG_DIR/m0.log" && grep -q READY "$DSG_DIR/m1.log" \
      && grep -q READY "$DSG_DIR/m2.log" && grep -q READY "$DSG_DIR/m3.log" \
      && break
    sleep 1
  done
  grep -q READY "$DSG_DIR/m3.log"
  dsg_wait_eps "$DSG_DIR/meps.json" 4 ""
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$DSG_DIR/meps.json" --model dec --requests 48 \
    --qps 400 --prompt-mix 2,4,24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_disagg_mono.json" \
    --assert-no-drops
  kill -9 $M0 $M1 $M2 $M3 2>/dev/null || true
  trap - EXIT
  DFLEET=127.0.0.1:9424,127.0.0.1:9425,127.0.0.1:9426,127.0.0.1:9427
  for r in 0 1 2 3; do
    env "${DSG_ENV[@]}" python tools/serve.py --model dec="$DSG_DIR/dec" \
      --rank $r --fleet "$DFLEET" --roles prefill,prefill,decode,decode \
      --decode-buckets 4,8 --decode-mode token \
      --endpoints-file "$DSG_DIR/deps.json" > "$DSG_DIR/d$r.log" 2>&1 &
    eval "D$r=\$!"
  done
  trap 'kill -9 $D0 $D1 $D2 $D3 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$DSG_DIR/d0.log" && grep -q READY "$DSG_DIR/d1.log" \
      && grep -q READY "$DSG_DIR/d2.log" && grep -q READY "$DSG_DIR/d3.log" \
      && break
    sleep 1
  done
  grep -q READY "$DSG_DIR/d3.log"
  dsg_wait_eps "$DSG_DIR/deps.json" 4 "prefill,prefill,decode,decode"
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$DSG_DIR/deps.json" --model dec --requests 48 \
    --qps 400 --prompt-mix 2,4,24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_disagg_pair.json" \
    --assert-no-drops
  # satellite: replicas republish the transfer counters and the
  # per-model cache-pressure gauges over the 1 s __metrics__ publish —
  # the role-aware autoscaler's decode signal rides kv_pool_occupancy
  python tools/metrics_dump.py --scrape 127.0.0.1:9424 --decode \
    | grep -c kv_xfer_blocks_total > /dev/null
  python tools/metrics_dump.py --scrape 127.0.0.1:9426 --decode \
    | grep -c kv_pool_occupancy > /dev/null
  python tools/metrics_dump.py --scrape 127.0.0.1:9426 --decode \
    | grep -c prefix_cache_hit_rate > /dev/null
  kill -9 $D0 $D1 $D2 $D3 2>/dev/null || true
  trap - EXIT
  python - "$DSG_DIR/BENCH_disagg_pair.json" \
    "$DSG_DIR/BENCH_disagg_mono.json" <<'EOF'
import json, sys
dis = json.load(open(sys.argv[1]))
mono = json.load(open(sys.argv[2]))
assert dis["outputs_sha256"] == mono["outputs_sha256"], \
    "disagg outputs differ from the monolith twin: %s != %s" \
    % (dis["outputs_sha256"], mono["outputs_sha256"])
rp = dis.get("role_phases")
assert rp and rp["disagg_requests"] > 0, \
    "no reply carried role=disagg phase attribution: %r" % (rp,)
print("disagg per-role p99: prefill queue %.1f ms, prefill %.1f ms, "
      "xfer %.1f ms, decode queue %.1f ms, decode exec %.1f ms "
      "(%d disagg requests)"
      % (rp["prefill"]["queue_wait_ms_p99"], rp["prefill"]["prefill_ms_p99"],
         rp["xfer"]["xfer_ms_p99"], rp["decode"]["queue_wait_ms_p99"],
         rp["decode"]["execute_ms_p99"], rp["disagg_requests"]))
for k in ("ttft_ms_p99", "itl_ms_p99"):
    d, m = dis[k], mono[k]
    ratio = d / max(m, 1e-9)
    print("%s: disagg %.1f ms vs monolith %.1f ms -> %.2fx" % (k, d, m, ratio))
    if ratio > 1.10:
        # sha parity + no-drops above are the hard gates; tail latency
        # on a loaded CI box degrades to a loud notice (the real capture
        # lives in BASELINE.md round 17)
        print("SKIP-NOTICE: disagg %s %.2fx > 1.10x of the monolith twin "
              "— parity gates passed" % (k, ratio))
print("bitwise-equal outputs OK (%d distinct prompts)"
      % dis["outputs_distinct"])
EOF
  echo "== disagg smoke: SIGKILL a prefill replica mid-transfer =="
  KFLEET=127.0.0.1:9428,127.0.0.1:9429,127.0.0.1:9430,127.0.0.1:9431
  for r in 0 1 2 3; do
    env "${DSG_ENV[@]}" FLAGS_tracing=1 \
      FLAGS_telemetry_dir="$DSG_DIR/tel" \
      python tools/serve.py --model dec="$DSG_DIR/dec" \
      --rank $r --fleet "$KFLEET" --roles prefill,prefill,decode,decode \
      --decode-buckets 4,8 --decode-mode token \
      --endpoints-file "$DSG_DIR/keps.json" > "$DSG_DIR/k$r.log" 2>&1 &
    eval "K$r=\$!"
  done
  trap 'kill -9 $K0 $K1 $K2 $K3 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$DSG_DIR/k0.log" && grep -q READY "$DSG_DIR/k1.log" \
      && grep -q READY "$DSG_DIR/k2.log" && grep -q READY "$DSG_DIR/k3.log" \
      && break
    sleep 1
  done
  grep -q READY "$DSG_DIR/k3.log"
  dsg_wait_eps "$DSG_DIR/keps.json" 4 "prefill,prefill,decode,decode"
  # long prompts keep sealed-block transfers in flight when the kill
  # lands; the surviving prefill absorbs the replays — zero drops
  ( sleep 1; kill -9 $K0 2>/dev/null || true ) &
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$DSG_DIR/keps.json" --model dec --requests 96 \
    --qps 40 --prompt-mix 24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_disagg_kill.json" \
    --assert-no-drops
  kill -9 $K1 $K2 $K3 2>/dev/null || true
  trap - EXIT
  # the victim's write-through flight recorder must already name its
  # in-flight transfer frames on disk (SIGKILL is uncatchable)
  grep -q kvxfer "$DSG_DIR/tel/flightrec-$K0.json"
  echo "flight recorder OK: victim flightrec-$K0.json names kvxfer frames"
  echo "== disagg smoke: int8 wire residency, pair vs pair vs monolith =="
  env "${DSG_ENV[@]}" python tools/serve.py --model dec="$DSG_DIR/dec" \
    --rank 0 --fleet 127.0.0.1:9432,127.0.0.1:9433 \
    --roles prefill,decode --decode-buckets 4,8 --decode-mode token \
    --endpoints-file "$DSG_DIR/f32eps.json" > "$DSG_DIR/f32p.log" 2>&1 &
  F0=$!
  env "${DSG_ENV[@]}" python tools/serve.py --model dec="$DSG_DIR/dec" \
    --rank 1 --fleet 127.0.0.1:9432,127.0.0.1:9433 \
    --roles prefill,decode --decode-buckets 4,8 --decode-mode token \
    --endpoints-file "$DSG_DIR/f32eps.json" > "$DSG_DIR/f32d.log" 2>&1 &
  F1=$!
  env "${DSG_ENV[@]}" FLAGS_kv_cache_dtype=int8 python tools/serve.py \
    --model dec="$DSG_DIR/dec" \
    --rank 0 --fleet 127.0.0.1:9434,127.0.0.1:9435 \
    --roles prefill,decode --decode-buckets 4,8 --decode-mode token \
    --endpoints-file "$DSG_DIR/i8eps.json" > "$DSG_DIR/i8p.log" 2>&1 &
  I0=$!
  env "${DSG_ENV[@]}" FLAGS_kv_cache_dtype=int8 python tools/serve.py \
    --model dec="$DSG_DIR/dec" \
    --rank 1 --fleet 127.0.0.1:9434,127.0.0.1:9435 \
    --roles prefill,decode --decode-buckets 4,8 --decode-mode token \
    --endpoints-file "$DSG_DIR/i8eps.json" > "$DSG_DIR/i8d.log" 2>&1 &
  I1=$!
  env "${DSG_ENV[@]}" FLAGS_kv_cache_dtype=int8 python tools/serve.py \
    --model dec="$DSG_DIR/dec" --port 9436 --decode-buckets 4,8 \
    --decode-mode token > "$DSG_DIR/i8m.log" 2>&1 &
  I2=$!
  trap 'kill -9 $F0 $F1 $I0 $I1 $I2 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$DSG_DIR/f32p.log" && grep -q READY "$DSG_DIR/f32d.log" \
      && grep -q READY "$DSG_DIR/i8p.log" && grep -q READY "$DSG_DIR/i8d.log" \
      && grep -q READY "$DSG_DIR/i8m.log" && break
    sleep 1
  done
  grep -q READY "$DSG_DIR/i8m.log"
  dsg_wait_eps "$DSG_DIR/f32eps.json" 2 "prefill,decode"
  dsg_wait_eps "$DSG_DIR/i8eps.json" 2 "prefill,decode"
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$DSG_DIR/f32eps.json" --model dec --requests 24 \
    --qps 200 --prompt-mix 24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_xfer_f32.json" --assert-no-drops
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$DSG_DIR/i8eps.json" --model dec --requests 24 \
    --qps 200 --prompt-mix 24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_xfer_int8.json" --assert-no-drops
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints 127.0.0.1:9436 --model dec --requests 24 \
    --qps 200 --prompt-mix 24 --max-new 8 --deadline-ms 60000 \
    --retry-shed 4 --out "$DSG_DIR/BENCH_xfer_int8_mono.json" \
    --assert-no-drops
  # scrape the wire counters off both prefill replicas BEFORE teardown
  python - <<'EOF'
import time
from paddle_tpu.core import telemetry
time.sleep(1.2)   # one __metrics__ publish period
def xfer_bytes(ep, dtype):
    snap = telemetry.scrape(ep)
    return sum(v for k, v in snap.get("counters", {}).items()
               if k.startswith("kv_xfer_bytes_total")
               and "dtype=%s" % dtype in k)
f32 = xfer_bytes("127.0.0.1:9432", "f32")
i8 = xfer_bytes("127.0.0.1:9434", "int8")
assert f32 > 0, "f32 pair never moved a sealed block"
assert i8 > 0, "int8 pair never moved a sealed block"
ratio = i8 / f32
print("kv_xfer_bytes_total: int8 %d B vs f32 %d B on the same traffic "
      "-> %.2fx" % (i8, f32, ratio))
assert ratio <= 0.55, \
    "int8 wire transfer %.2fx > 0.55x of f32 bytes" % ratio
EOF
  kill -9 $F0 $F1 $I0 $I1 $I2 2>/dev/null || true
  trap - EXIT
  python - "$DSG_DIR/BENCH_xfer_int8.json" \
    "$DSG_DIR/BENCH_xfer_int8_mono.json" <<'EOF'
import json, sys
pair = json.load(open(sys.argv[1]))
mono = json.load(open(sys.argv[2]))
assert pair["outputs_sha256"] == mono["outputs_sha256"], \
    "int8 pair outputs differ from the int8 monolith: %s != %s" \
    % (pair["outputs_sha256"], mono["outputs_sha256"])
print("int8 pair == int8 monolith outputs OK (%d distinct prompts)"
      % pair["outputs_distinct"])
EOF
  rm -rf "$DSG_DIR"
  echo "CI --disagg-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--migrate-smoke" ]; then
  # live decode-session migration leg: the export/adopt/resume unit
  # tests, then two fleet scenarios.  Crash: a 3-replica fleet is
  # warmed per-replica with the SAME seeded Poisson traffic (every
  # replica then holds the full prompt ++ out history chain of every
  # generation, evictable in its prefix index), one replica is
  # SIGKILLed mid-decode under load — every request must answer, the
  # resumed outputs_sha256 must equal the uninterrupted twin's, the
  # worst resumed session re-feeds under one KV block (the chain
  # matched instead of re-prefilling), the victim's write-through
  # flight recorder names its in-flight sessions, and
  # executor_cache_miss_total stays flat on the survivors.  Drain: an
  # autoscale-down __retire__ with FLAGS_migrate_on_drain pushes the
  # victim's live sessions to its peers over __kvxfer__ — zero drops,
  # parity again, and the victim exits promptly: the resumed sessions
  # prove hand-off, not completion-wait
  echo "== migrate smoke: session-migration unit tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_session_migration.py -q
  MIG_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-decoder "$MIG_DIR/dec"
  MIG_ENV=(JAX_PLATFORMS=cpu FLAGS_telemetry=1
           FLAGS_kv_block_size=8 FLAGS_kv_cache_blocks=768
           FLAGS_serving_hb_interval=0.2 FLAGS_serving_hb_timeout=1.5
           FLAGS_compile_cache_dir="$MIG_DIR/cc")
  mig_wait_eps() {
    python - "$1" "$2" <<'EOF'
import json, sys, time
path, want_n = sys.argv[1], int(sys.argv[2])
deadline = time.time() + 30
while time.time() < deadline:
    try:
        if len(json.load(open(path)).get("endpoints", [])) == want_n:
            sys.exit(0)
    except Exception:
        pass
    time.sleep(0.3)
sys.exit("%s never published %d endpoints" % (path, want_n))
EOF
  }
  echo "== migrate smoke: SIGKILL a replica mid-decode, clients resume =="
  CFLEET=127.0.0.1:9490,127.0.0.1:9491,127.0.0.1:9492
  for r in 0 1 2; do
    env "${MIG_ENV[@]}" FLAGS_tracing=1 \
      FLAGS_telemetry_dir="$MIG_DIR/tel" \
      python tools/serve.py --model dec="$MIG_DIR/dec" \
      --rank $r --fleet "$CFLEET" --decode-buckets 4,8 \
      --decode-mode token --endpoints-file "$MIG_DIR/ceps.json" \
      > "$MIG_DIR/c$r.log" 2>&1 &
    eval "C$r=\$!"
  done
  trap 'kill -9 $C0 $C1 $C2 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$MIG_DIR/c0.log" && grep -q READY "$MIG_DIR/c1.log" \
      && grep -q READY "$MIG_DIR/c2.log" && break
    sleep 1
  done
  grep -q READY "$MIG_DIR/c2.log"
  mig_wait_eps "$MIG_DIR/ceps.json" 3
  # warmth: replay the same seeded traffic against EACH replica
  # individually, so whichever survivor a crashed stream fails over to
  # already holds the session's full history chain; the last pass
  # doubles as the uninterrupted parity twin (same seed, same prompts)
  for port in 9490 9491 9492; do
    JAX_PLATFORMS=cpu python tools/loadgen.py \
      --endpoints 127.0.0.1:$port --model dec --requests 48 --qps 60 \
      --prompt-mix 8,16,24 --max-new 16 --deadline-ms 60000 \
      --retry-shed 4 --seed 20 --out "$MIG_DIR/BENCH_migrate_twin.json" \
      --assert-no-drops
  done
  # survivor compile-cache baseline: crash resume must reuse the
  # prewarmed lane buckets, so the miss counter may not move again
  python - "$MIG_DIR/miss0.json" <<'EOF'
import json, sys, time
from paddle_tpu.core import telemetry
time.sleep(1.2)   # one __metrics__ publish period
out = {}
for ep in ("127.0.0.1:9491", "127.0.0.1:9492"):
    snap = telemetry.scrape(ep)
    out[ep] = sum(v for k, v in snap.get("counters", {}).items()
                  if k.startswith("executor_cache_miss_total"))
json.dump(out, open(sys.argv[1], "w"))
EOF
  ( sleep 1.5; kill -9 $C0 2>/dev/null || true ) &
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$MIG_DIR/ceps.json" --model dec --requests 48 \
    --qps 30 --prompt-mix 8,16,24 --max-new 16 --deadline-ms 60000 \
    --retry-shed 4 --seed 20 --out "$MIG_DIR/BENCH_migrate_kill.json" \
    --assert-no-drops
  # the victim's write-through flight recorder must already name its
  # in-flight decode sessions on disk (req_ids ride the decode_step
  # notes; SIGKILL is uncatchable)
  grep -q decode_step "$MIG_DIR/tel/flightrec-$C0.json"
  echo "flight recorder OK: victim flightrec-$C0.json names live sessions"
  { python tools/metrics_dump.py --scrape 127.0.0.1:9491 --decode;
    python tools/metrics_dump.py --scrape 127.0.0.1:9492 --decode; } \
    | grep -c kv_migrate_resume_total > /dev/null
  python - "$MIG_DIR/BENCH_migrate_kill.json" \
    "$MIG_DIR/BENCH_migrate_twin.json" "$MIG_DIR/miss0.json" <<'EOF'
import json, sys, time
from paddle_tpu.core import telemetry
kill = json.load(open(sys.argv[1]))
twin = json.load(open(sys.argv[2]))
miss0 = json.load(open(sys.argv[3]))
assert kill["statuses"].get("ok") == kill["requests"], \
    "not every request answered across the SIGKILL: %s" % kill["statuses"]
assert kill["outputs_sha256"] == twin["outputs_sha256"], \
    "resumed outputs differ from the uninterrupted twin: %s != %s" \
    % (kill["outputs_sha256"], twin["outputs_sha256"])
res = kill.get("resume")
assert res and res["resumed_requests"] >= 1, \
    "no stream crash-resumed across the kill: %r" % (res,)
assert res["reprefill_tokens_max"] < 8, \
    "a resumed session re-fed %d tokens (>= one 8-token KV block): %r" \
    % (res["reprefill_tokens_max"], res["rows"])
time.sleep(1.2)   # one __metrics__ publish period
for ep, before in miss0.items():
    snap = telemetry.scrape(ep)
    after = sum(v for k, v in snap.get("counters", {}).items()
                if k.startswith("executor_cache_miss_total"))
    assert after == before, \
        "executor_cache_miss_total moved on %s: %s -> %s" \
        % (ep, before, after)
print("crash leg OK: %d resumed sessions, worst re-feed %d tokens, "
      "sha parity with the twin, survivor compile caches flat"
      % (res["resumed_requests"], res["reprefill_tokens_max"]))
EOF
  kill -9 $C1 $C2 2>/dev/null || true
  trap - EXIT
  echo "== migrate smoke: autoscale-down retirement drains by migration =="
  EFLEET=127.0.0.1:9494,127.0.0.1:9495,127.0.0.1:9496
  for r in 0 1 2; do
    # the retirement victim (rank 2) decodes with an injected 100 ms
    # per-iteration delay — its sessions are deterministically still
    # live when the drain scans, so the leg proves hand-off, not luck
    FS=""
    if [ "$r" = 2 ]; then FS="serving.decode_step:delay:1"; fi
    env "${MIG_ENV[@]}" FLAGS_migrate_on_drain=1 FLAGS_fault_spec="$FS" \
      python tools/serve.py --model dec="$MIG_DIR/dec" \
      --rank $r --fleet "$EFLEET" --decode-buckets 4,8 \
      --decode-mode token --endpoints-file "$MIG_DIR/eeps.json" \
      > "$MIG_DIR/e$r.log" 2>&1 &
    eval "E$r=\$!"
  done
  trap 'kill -9 $E0 $E1 $E2 2>/dev/null || true' EXIT
  for _ in $(seq 120); do
    grep -q READY "$MIG_DIR/e0.log" && grep -q READY "$MIG_DIR/e1.log" \
      && grep -q READY "$MIG_DIR/e2.log" && break
    sleep 1
  done
  grep -q READY "$MIG_DIR/e2.log"
  mig_wait_eps "$MIG_DIR/eeps.json" 3
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$MIG_DIR/eeps.json" --model dec --requests 60 \
    --qps 40 --prompt-mix 16,24 --max-new 24 --deadline-ms 60000 \
    --retry-shed 6 --seed 21 --out "$MIG_DIR/BENCH_drain_twin.json" \
    --assert-no-drops
  # retire rank 2 mid-flight: the coordinator stays up, the victim
  # drains by PUSHING its live sessions to the surviving peers
  ( sleep 0.7; python - <<'EOF'
import numpy as np
from paddle_tpu.native import rpc
from paddle_tpu.serving import codec
c = rpc.RpcClient("127.0.0.1:9496", connect_timeout=2.0,
                  rpc_deadline=5.0, retry_times=0)
try:
    c.send_var(codec.RETIRE_KEY, np.asarray([0], np.int64))
finally:
    c.close()
EOF
  ) &
  JAX_PLATFORMS=cpu python tools/loadgen.py \
    --endpoints-file "$MIG_DIR/eeps.json" --model dec --requests 60 \
    --qps 40 --prompt-mix 16,24 --max-new 24 --deadline-ms 60000 \
    --retry-shed 6 --seed 21 --out "$MIG_DIR/BENCH_migrate_drain.json" \
    --assert-no-drops
  # zero completion-wait stalls: the drained victim must exit promptly
  # (its 24-token generations moved, they were not waited out)
  for _ in $(seq 80); do
    kill -0 $E2 2>/dev/null || break
    sleep 0.5
  done
  if kill -0 $E2 2>/dev/null; then
    echo "CI --migrate-smoke: FAIL (retired replica never exited)"
    exit 1
  fi
  python - "$MIG_DIR/BENCH_migrate_drain.json" \
    "$MIG_DIR/BENCH_drain_twin.json" <<'EOF'
import json, sys, time
from paddle_tpu.core import telemetry
drain = json.load(open(sys.argv[1]))
twin = json.load(open(sys.argv[2]))
assert drain["statuses"].get("ok") == drain["requests"], \
    "not every request answered across the retirement: %s" \
    % drain["statuses"]
assert drain["outputs_sha256"] == twin["outputs_sha256"], \
    "post-drain outputs differ from the uninterrupted twin: %s != %s" \
    % (drain["outputs_sha256"], twin["outputs_sha256"])
res = drain.get("resume")
assert res and res["resumed_requests"] >= 1, \
    "retirement migrated no live session (completion-wait drain?): %r" \
    % (res,)
time.sleep(1.2)   # one __metrics__ publish period
accepted = 0
for ep in ("127.0.0.1:9494", "127.0.0.1:9495"):
    snap = telemetry.scrape(ep)
    accepted += sum(
        v for k, v in snap.get("counters", {}).items()
        if k.startswith("kv_migrate_resume_total")
        and "result=accepted" in k)
assert accepted >= 1, "no survivor admitted a migrated session"
print("drain leg OK: %d sessions followed the hand-off, %d resume "
      "admissions on the survivors, sha parity with the twin"
      % (res["resumed_requests"], accepted))
EOF
  kill -9 $E0 $E1 2>/dev/null || true
  trap - EXIT
  rm -rf "$MIG_DIR"
  echo "CI --migrate-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--ckpt-smoke" ]; then
  # checkpoint leg: manager unit tests (async writer, sharded layout,
  # crash consistency, temp GC, validity cache), then the stall probe —
  # an async save may not stall the step loop more than 5% of a step
  # (the BASELINE validity bar) — and the telemetry round trip through
  # the metrics_dump --checkpoint CLI filter
  echo "== ckpt smoke: checkpoint manager tests =="
  JAX_PLATFORMS=cpu FLAGS_static_check=error \
    python -m pytest tests/test_checkpoint_resume.py -q
  echo "== ckpt smoke: async save stall probe (<5% of step) =="
  CKPT_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu FLAGS_telemetry=1 FLAGS_telemetry_dir="$CKPT_DIR/tel" \
    python tools/ckpt_stall_probe.py --steps 16 --save-every 4 \
      --batch 4096 --hidden 512 --ckpt-dir "$CKPT_DIR/ckpt" \
      --assert-stall-frac 0.05 --out "$CKPT_DIR/probe.json"
  echo "== ckpt smoke: metrics_dump --checkpoint round trip =="
  python tools/metrics_dump.py --json "$CKPT_DIR/tel/metrics.json" \
    --checkpoint --prom | grep -q checkpoint_save_stall_ms
  rm -rf "$CKPT_DIR"
  echo "CI --ckpt-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--trace-smoke" ]; then
  # distributed-tracing leg: the tracing unit tests, then a live
  # 2-replica fleet under FLAGS_tracing=1 — the per-process trace JSONL
  # files must merge into one Perfetto-loadable trace.json containing at
  # least one cross-process flow (client span -> replica span)
  echo "== trace smoke: tracing tests =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py -q
  echo "== trace smoke: 2-replica fleet under FLAGS_tracing=1 =="
  TRC_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu python tools/serve.py --save-demo-model "$TRC_DIR/model"
  TRC_ENV=(JAX_PLATFORMS=cpu FLAGS_tracing=1 FLAGS_telemetry=1
           FLAGS_telemetry_dir="$TRC_DIR/tel"
           FLAGS_serving_hb_interval=0.2 FLAGS_serving_hb_timeout=1.5
           FLAGS_compile_cache_dir="$TRC_DIR/cc")
  env "${TRC_ENV[@]}" python tools/serve.py --model fc="$TRC_DIR/model" \
    --rank 0 --fleet 127.0.0.1:9470,127.0.0.1:9471 --buckets 1,4 \
    --endpoints-file "$TRC_DIR/eps.json" > "$TRC_DIR/r0.log" 2>&1 &
  T0=$!
  env "${TRC_ENV[@]}" python tools/serve.py --model fc="$TRC_DIR/model" \
    --rank 1 --fleet 127.0.0.1:9470,127.0.0.1:9471 --buckets 1,4 \
    --endpoints-file "$TRC_DIR/eps.json" > "$TRC_DIR/r1.log" 2>&1 &
  T1=$!
  trap 'kill -9 $T0 $T1 2>/dev/null || true' EXIT
  for _ in $(seq 60); do
    grep -q READY "$TRC_DIR/r0.log" && grep -q READY "$TRC_DIR/r1.log" \
      && break
    sleep 1
  done
  grep -q READY "$TRC_DIR/r0.log" && grep -q READY "$TRC_DIR/r1.log"
  env "${TRC_ENV[@]}" python tools/loadgen.py \
    --endpoints-file "$TRC_DIR/eps.json" --model fc --requests 40 \
    --qps 40 --out "$TRC_DIR/BENCH_serving.json" --assert-no-drops
  kill $T0 $T1 2>/dev/null || true
  wait $T0 $T1 2>/dev/null || true
  trap - EXIT
  # one trace.json over client + both replicas, >=1 cross-process flow
  python tools/trace_view.py --telemetry_dir "$TRC_DIR/tel" \
    --out "$TRC_DIR/trace.json" --require-flow
  python -c "import json,sys; json.load(open(sys.argv[1]))" \
    "$TRC_DIR/trace.json"
  rm -rf "$TRC_DIR"
  echo "CI --trace-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--obs-smoke" ]; then
  # observability fast leg: telemetry + timeline-tool tests, then a tiny
  # telemetry-on executor run dumped and re-read through the CLI
  echo "== obs smoke: telemetry + timeline tests =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py \
    tests/test_timeline_tool.py tests/test_profiler_metrics.py -q
  echo "== obs smoke: dump -> metrics_dump round trip =="
  OBS_DIR="$(mktemp -d)"
  JAX_PLATFORMS=cpu FLAGS_telemetry=1 FLAGS_telemetry_dir="$OBS_DIR" \
    python tools/profile_bert_step.py --steps 2 --tiny --no-trace
  python tools/metrics_dump.py --json "$OBS_DIR/metrics.json"
  python tools/metrics_dump.py --json "$OBS_DIR/metrics.json" --prom \
    | grep -q executor_steps_total
  rm -rf "$OBS_DIR"
  echo "CI --obs-smoke: PASS"
  exit 0
fi

if [ "$MODE" = "--layout-smoke" ]; then
  # layout/carry fast leg: the HLO-level regression test (compiled AMP
  # step has no per-step f32 converts of carried params) plus a tiny
  # 2-step CPU dry pass of the profiler harness with the HBM audit on
  echo "== layout smoke: HLO regression test =="
  JAX_PLATFORMS=cpu python -m pytest tests/test_layout_match.py -q
  echo "== layout smoke: profile_bert_step CPU dry pass =="
  JAX_PLATFORMS=cpu python tools/profile_bert_step.py --steps 2 --tiny \
    --audit --no-trace
  echo "CI --layout-smoke: PASS"
  exit 0
fi

echo "== native build (compiles on import) =="
python -c "import paddle_tpu.native; print('native OK')"

echo "== unit + integration tests (virtual 8-device CPU mesh) =="
case "$MODE" in
  quick)
    python -m pytest tests/ -x -q -k "not subprocess and not torch_parity" ;;
  tpu)
    # real-chip tier (needs a TPU host)
    PADDLE_TPU_TESTS=1 python -m pytest tests/ -m tpu -q ;;
  *)
    python -m pytest tests/ -x -q ;;
esac

echo "== multichip dryrun (8-device virtual mesh) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

if [ "$MODE" = "tpu" ]; then
  echo "== bench (real chip) =="
  python bench.py
fi

echo "CI $MODE: PASS"
