"""Probe: Pallas fused dropout+add+LN vs the XLA-composed emission.

Flagship BERT shape [32768, 768] bf16 (bs256 x seq128).  The composed
variant reproduces the training emission the ops lower to today:
byte-threshold dropout mask (ops/common.py bernoulli_bytes), residual
add, LayerNorm with f32-internal stats.  Chained+barrier protocol per
bench_util (the round-2 per-call harness measured the tunnel, not the
chip).
"""

import os
import sys

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_util import timed as _time, tunnel_rtt as _rtt
from paddle_tpu.pallas_kernels.fused_ln import fused_dropout_add_ln
from paddle_tpu.ops.common import bernoulli_bytes, realized_keep_prob

REP = 32
P = 0.1
EPS = 1e-5


def composed(x, y, g, b, key, p):
    if p > 0:
        keep = bernoulli_bytes(key, 1.0 - p, y.shape)
        q = realized_keep_prob(1.0 - p)
        y = jnp.where(keep, y / jnp.asarray(q, y.dtype),
                      jnp.asarray(0.0, y.dtype))
    r = x + y
    rf = r.astype(jnp.float32)
    mean = rf.mean(-1, keepdims=True)
    c = rf - mean
    var = (c * c).mean(-1, keepdims=True)
    z = c * lax.rsqrt(var + EPS) * g + b
    return z.astype(x.dtype)


def chain_fwd(fn, x, y, g, b, rep):
    def body(c, i):
        xb, cb = lax.optimization_barrier((x, c))
        z = fn(xb, y, g, b, i)
        zb = lax.optimization_barrier(z)
        return zb.reshape(-1)[0].astype(jnp.float32) * 1e-9 + cb * 0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), jnp.arange(rep))
    return (out,)


def chain_bwd(fn, x, y, g, b, rep):
    def loss(x, y, g, b, i):
        z = fn(x, y, g, b, i)
        return (z.astype(jnp.float32) ** 2).sum() * 1e-9

    grad = jax.grad(loss, (0, 1, 2, 3))

    def body(c, i):
        xb, cb = lax.optimization_barrier((x, c))
        gs = grad(xb, y, g, b, i)
        gb = lax.optimization_barrier(gs)
        return gb[0].reshape(-1)[0].astype(jnp.float32) * 1e-9 + cb * 0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), jnp.arange(rep))
    return (out,)


def main():
    rtt = _rtt()
    print(f"device: {jax.devices()[0]}  RTT {rtt*1e3:.1f} ms")
    key = jax.random.PRNGKey(0)
    N, H = 32768, 768
    x = jax.random.normal(key, (N, H), jnp.bfloat16)
    y = jax.random.normal(jax.random.fold_in(key, 1), (N, H), jnp.bfloat16)
    g = jnp.ones((H,), jnp.float32)
    b = jnp.zeros((H,), jnp.float32)

    def run(name, fn, chain):
        t = _time(lambda *a: chain(fn, *a, REP), x, y, g, b)
        dev = max(t - rtt, 1e-9) / REP
        # fwd traffic: read x,y write z = 3 passes of N*H*2B
        print(f"{name:44s} {dev*1e3:7.3f} ms")
        return dev

    for p in (0.0, P):
        co = lambda x, y, g, b, i, p=p: composed(
            x, y, g, b, jax.random.fold_in(key, i), p)
        fu = lambda x, y, g, b, i, p=p: fused_dropout_add_ln(
            x, y, g, b, p, jnp.stack([i.astype(jnp.uint32),
                                      jnp.uint32(7)]), EPS)
        a = run(f"composed fwd          p={p}", co, chain_fwd)
        c = run(f"pallas fused fwd      p={p}", fu, chain_fwd)
        print(f"  -> fwd speedup {a/c:.2f}x")
        a = run(f"composed fwd+bwd      p={p}", co, chain_bwd)
        c = run(f"pallas fused fwd+bwd  p={p}", fu, chain_bwd)
        print(f"  -> fwd+bwd speedup {a/c:.2f}x")


if __name__ == "__main__":
    main()
