"""Pure-JAX ResNet-50 train-step reference: what can this chip really do?

Strips the framework away: hand-rolled ResNet-50 (lax.conv + train-mode BN
+ momentum SGD, bf16 AMP carry exactly like models/resnet.py), donated
params, 5-step dispatch chunks with host-fetch sync — the same protocol as
bench.py.  Establishes the device-capability anchor for the framework's
emission to match.

Env: PJ_LAYOUT=NCHW|NHWC  PJ_BATCH=512  PJ_ITERS=30
"""

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

LAYOUT = os.environ.get("PJ_LAYOUT", "NCHW")
BATCH = int(os.environ.get("PJ_BATCH", "512"))
ITERS = int(os.environ.get("PJ_ITERS", "30"))
# fusion-structure experiments: keep BN stats / optimizer updates OUT of
# the conv fusions (the profile shows conv+epilogue fusions at ~19% MXU
# while isolated convs hit 130-190 TF/s)
BARRIER_CONV = os.environ.get("PJ_BARRIER_CONV", "0") == "1"
BARRIER_OPT = os.environ.get("PJ_BARRIER_OPT", "0") == "1"

# (blocks, out_channels) per stage for ResNet-50
STAGES = [(3, 256), (4, 512), (6, 1024), (3, 2048)]


def conv(x, w, stride=1):
    if LAYOUT == "NCHW":
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        dn = ("NHWC", "HWIO", "NHWC")
    kh = w.shape[2] if LAYOUT == "NCHW" else w.shape[0]
    pad = (kh - 1) // 2
    y = lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn, preferred_element_type=jnp.bfloat16)
    if BARRIER_CONV:
        y = lax.optimization_barrier(y)
    return y


# y-saving BN: backward reconstructs xhat from the PRE-relu output y
# ((y - beta)/gamma) instead of re-reading the conv output x, removing one
# full-tensor read from every BN backward fusion.  The closed-form dx
# includes the mean/var paths, so gradients match plain autodiff BN.
Y_SAVING = os.environ.get("PJ_YSAVE", "0") == "1"


@jax.custom_vjp
def _bn_train_core(x, g, b):
    y, _, _ = _bn_train_fwd_math(x, g, b)
    return y


def _bn_train_fwd_math(x, g, b, eps=1e-5):
    c_ax = 1 if LAYOUT == "NCHW" else 3
    axes = tuple(i for i in range(4) if i != c_ax)
    cshape = [1, 1, 1, 1]
    cshape[c_ax] = x.shape[c_ax]
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes)
    msq = jnp.mean(jnp.square(xf), axis=axes)
    v = msq - jnp.square(m)
    inv = jax.lax.rsqrt(v + eps)
    a = (inv * g).reshape(cshape)
    bb = (b - m * inv * g).reshape(cshape)
    y = x * a.astype(x.dtype) + bb.astype(x.dtype)
    return y, m, inv


def _bn_core_fwd(x, g, b):
    y, m, inv = _bn_train_fwd_math(x, g, b)
    return y, (y, g, b, m, inv)


def _bn_core_bwd(res, dy):
    y, g, b, m, inv = res
    c_ax = 1 if LAYOUT == "NCHW" else 3
    axes = tuple(i for i in range(4) if i != c_ax)
    cshape = [1, 1, 1, 1]
    cshape[c_ax] = y.shape[c_ax]
    n = 1
    for i in axes:
        n *= y.shape[i]
    f32 = jnp.float32
    dyf = dy.astype(f32)
    yf = y.astype(f32)
    s1 = jnp.sum(dyf, axis=axes)
    sdy_y = jnp.sum(dyf * yf, axis=axes)
    u = 1.0 / g
    s2 = u * sdy_y + (-b * u) * s1      # = sum(dy * xhat)
    gi = g * inv
    # dx = gi*(dy - S1/n - xhat*S2/n); gi*xhat = inv*(y - b), so the y
    # coefficient is plain inv (NOT inv/g — xhat's 1/g cancels against gi)
    a1 = gi.reshape(cshape)
    a2 = (-inv * s2 / n).reshape(cshape)
    a3 = ((-gi * s1 + inv * b * s2) / n).reshape(cshape)
    dx = (dy * a1.astype(dy.dtype) + y * a2.astype(y.dtype)
          + a3.astype(dy.dtype))
    return dx, s2.astype(g.dtype), s1.astype(b.dtype)


_bn_train_core.defvjp(_bn_core_fwd, _bn_core_bwd)


def bn(x, p, state, name, momentum=0.9, eps=1e-5):
    if Y_SAVING:
        c_ax = 1 if LAYOUT == "NCHW" else 3
        axes = tuple(i for i in range(4) if i != c_ax)
        xf = jax.lax.stop_gradient(x).astype(jnp.float32)
        m = jnp.mean(xf, axis=axes)
        v = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(m)
        state[name + "_mean"] = (momentum * state[name + "_mean"]
                                 + (1 - momentum) * m)
        state[name + "_var"] = (momentum * state[name + "_var"]
                                + (1 - momentum) * v)
        return _bn_train_core(x, p[name + "_g"], p[name + "_b"])
    return _bn_plain(x, p, state, name, momentum, eps)


def _bn_plain(x, p, state, name, momentum=0.9, eps=1e-5):
    c_ax = 1 if LAYOUT == "NCHW" else 3
    axes = tuple(i for i in range(4) if i != c_ax)
    cshape = [1, 1, 1, 1]
    cshape[c_ax] = x.shape[c_ax]
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=axes)
    msq = jnp.mean(jnp.square(xf), axis=axes)
    v = msq - jnp.square(m)
    state[name + "_mean"] = momentum * state[name + "_mean"] + (1 - momentum) * m
    state[name + "_var"] = momentum * state[name + "_var"] + (1 - momentum) * v
    inv = 1.0 / jnp.sqrt(v + eps)
    a = (inv * p[name + "_g"]).reshape(cshape)
    b = (p[name + "_b"] - m * inv * p[name + "_g"]).reshape(cshape)
    return x * a.astype(x.dtype) + b.astype(x.dtype)


def make_params(key):
    p = {}

    def cw(name, o, i, k):
        nonlocal key
        key, sub = jax.random.split(key)
        fan = i * k * k
        w = jax.random.normal(sub, (o, i, k, k), jnp.float32) * np.sqrt(
            2.0 / fan)
        if LAYOUT != "NCHW":
            w = jnp.transpose(w, (2, 3, 1, 0))
        p[name] = w

    def bnp(name, c):
        p[name + "_g"] = jnp.ones((c,), jnp.float32)
        p[name + "_b"] = jnp.zeros((c,), jnp.float32)

    cw("conv0", 64, 3, 7)
    bnp("bn0", 64)
    cin = 64
    for si, (blocks, cout) in enumerate(STAGES):
        mid = cout // 4
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            cw(pre + "c1", mid, cin, 1)
            bnp(pre + "n1", mid)
            cw(pre + "c2", mid, mid, 3)
            bnp(pre + "n2", mid)
            cw(pre + "c3", cout, mid, 1)
            bnp(pre + "n3", cout)
            if bi == 0:
                cw(pre + "cs", cout, cin, 1)
                bnp(pre + "ns", cout)
            cin = cout
    key, sub = jax.random.split(key)
    p["fc_w"] = jax.random.normal(sub, (2048, 1000), jnp.float32) * 0.01
    p["fc_b"] = jnp.zeros((1000,), jnp.float32)
    return p


def make_state(p):
    s = {}
    for k in p:
        if k.endswith("_g"):
            c = p[k].shape[0]
            s[k[:-2] + "_mean"] = jnp.zeros((c,), jnp.float32)
            s[k[:-2] + "_var"] = jnp.ones((c,), jnp.float32)
    return s


# space-to-depth stem (PJ_S2D=1): the 7x7 s2 conv on 3 channels maps badly
# onto the MXU (contraction 147); rearranging 2x2 input blocks into 12
# channels turns it into an exactly-equivalent 4x4 s1 conv (contraction
# 192, measured vs the reference emission on CPU to 7e-7)
S2D = os.environ.get("PJ_S2D", "0") == "1"


def _s2d_weight(w):
    w8 = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    return w8.reshape(64, 3, 4, 2, 4, 2).transpose(
        0, 1, 3, 5, 2, 4).reshape(64, 12, 4, 4)


def forward(p, state, x):
    x = x.astype(jnp.bfloat16)
    if S2D and LAYOUT == "NCHW":
        N, _, H, W = x.shape
        xs = x.reshape(N, 3, H // 2, 2, W // 2, 2).transpose(
            0, 1, 3, 5, 2, 4).reshape(N, 12, H // 2, W // 2)
        w12 = _s2d_weight(p["conv0"]).astype(jnp.bfloat16)
        x = lax.conv_general_dilated(
            xs, w12, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.bfloat16)
    else:
        x = conv(x, p["conv0"].astype(jnp.bfloat16), 2)
    x = bn(x, p, state, "bn0")
    x = jnp.maximum(x, 0)
    if LAYOUT == "NCHW":
        window, strides = (1, 1, 3, 3), (1, 1, 2, 2)
        pads = ((0, 0), (0, 0), (1, 1), (1, 1))
    else:
        window, strides = (1, 3, 3, 1), (1, 2, 2, 1)
        pads = ((0, 0), (1, 1), (1, 1), (0, 0))
    x = lax.reduce_window(x, -np.inf, lax.max, window, strides, pads)
    cin = 64
    for si, (blocks, cout) in enumerate(STAGES):
        for bi in range(blocks):
            pre = f"s{si}b{bi}"
            stride = 2 if (si > 0 and bi == 0) else 1
            h = conv(x, p[pre + "c1"].astype(jnp.bfloat16), 1)
            h = jnp.maximum(bn(h, p, state, pre + "n1"), 0)
            h = conv(h, p[pre + "c2"].astype(jnp.bfloat16), stride)
            h = jnp.maximum(bn(h, p, state, pre + "n2"), 0)
            h = conv(h, p[pre + "c3"].astype(jnp.bfloat16), 1)
            h = bn(h, p, state, pre + "n3")
            if bi == 0:
                sc = conv(x, p[pre + "cs"].astype(jnp.bfloat16), stride)
                sc = bn(sc, p, state, pre + "ns")
            else:
                sc = x
            x = jnp.maximum(h + sc, 0)
        cin = cout
    axes = (2, 3) if LAYOUT == "NCHW" else (1, 2)
    x = jnp.mean(x.astype(jnp.float32), axis=axes)
    logits = x @ p["fc_w"] + p["fc_b"]
    return logits


def loss_fn(p, state, x, y):
    state = dict(state)
    logits = forward(p, state, x)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, y, axis=1))
    return loss, state


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def train_step(p, vel, state, x, y, lr=0.1, mu=0.9):
    (loss, new_state), g = jax.value_and_grad(loss_fn, has_aux=True)(
        p, state, x, y)
    if BARRIER_OPT:
        g = lax.optimization_barrier(g)
    new_p, new_vel = {}, {}
    for k in p:
        v = mu * vel[k] + g[k]
        new_vel[k] = v
        new_p[k] = p[k] - lr * v
    return new_p, new_vel, new_state, loss


def main():
    print(f"device={jax.devices()[0]} layout={LAYOUT} batch={BATCH}")
    key = jax.random.PRNGKey(0)
    p = make_params(key)
    state = make_state(p)
    vel = {k: jnp.zeros_like(v) for k, v in p.items()}
    rng = np.random.RandomState(0)
    if LAYOUT == "NCHW":
        xs = rng.rand(BATCH, 3, 224, 224).astype("float32")
    else:
        xs = rng.rand(BATCH, 224, 224, 3).astype("float32")
    x = jax.device_put(xs)
    y = jax.device_put(rng.randint(0, 1000, (BATCH, 1)))

    for _ in range(5):
        p, vel, state, loss = train_step(p, vel, state, x, y)
    np.asarray(loss)
    times = []
    chunk = 5
    for _ in range(max(ITERS // chunk, 1)):
        t0 = time.perf_counter()
        for _ in range(chunk):
            p, vel, state, loss = train_step(p, vel, state, x, y)
        np.asarray(loss)
        times.append((time.perf_counter() - t0) / chunk)
    med = float(np.median(times))
    print(f"step {med*1e3:.1f} ms  -> {BATCH/med:.1f} img/s  "
          f"loss={float(np.asarray(loss)):.4f}")


if __name__ == "__main__":
    main()
