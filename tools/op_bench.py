"""Config-driven per-op micro-benchmark harness (parity:
paddle/fluid/operators/benchmark/op_tester.cc + op_tester_config.h).

Config: a JSON file (or inline dict) describing one or more ops::

    [
      {"op_type": "matmul",
       "inputs": {"X": {"dims": [64, 1024], "dtype": "fp32",
                        "initializer": "random"},
                  "Y": {"dims": [1024, 1024]}},
       "attrs": {"transpose_X": false},
       "repeat": 100, "device": "tpu"}
    ]

dtypes: fp32/fp64/int32/int64 (reference spellings accepted).
initializers: random | natural | zeros (op_tester_config.h:33-40).

Usage: python tools/op_bench.py <config.json> [--device cpu|tpu]
Prints one JSON line per op: {"op_type", "device", "repeat",
"mean_ms", "p50_ms", "min_ms"}.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPES = {"fp32": "float32", "float": "float32", "fp64": "float64",
           "double": "float64", "int32": "int32", "int": "int32",
           "int64": "int64", "long": "int64",
           "float32": "float32", "float64": "float64"}


def _make_input(spec, rng):
    dims = [int(d) for d in spec["dims"]]
    dtype = _DTYPES[spec.get("dtype", "fp32")]
    init = spec.get("initializer", "random")
    if init == "random":
        a = rng.rand(*dims) if dtype.startswith("float") else rng.randint(
            0, spec.get("max_value", 10), dims)
    elif init == "natural":
        a = np.arange(int(np.prod(dims))).reshape(dims)
    elif init == "zeros":
        a = np.zeros(dims)
    elif init == "file":
        a = np.load(spec["filename"])
    else:
        raise ValueError("unknown initializer %r" % init)
    return np.asarray(a, dtype)


def bench_op(cfg, device=None):
    import paddle_tpu as fluid
    from paddle_tpu.core.registry import get_op_def

    op_type = cfg["op_type"]
    opdef = get_op_def(op_type)
    repeat = int(cfg.get("repeat", 50))
    warmup = int(cfg.get("warmup", 5))
    dev = device or cfg.get("device", "cpu")

    rng = np.random.RandomState(int(cfg.get("seed", 0)))
    feeds = {}
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inputs = {}
        for slot, spec in cfg.get("inputs", {}).items():
            name = "in_%s" % slot
            arr = _make_input(spec, rng)
            v = fluid.layers.data(name, shape=list(arr.shape[1:]),
                                  dtype=str(arr.dtype))
            feeds[name] = arr
            inputs[slot] = [v]
        block = main.global_block()
        outs = {}
        fetch = []
        for oslot in opdef.output_slots:
            ov = block.create_var(
                name="out_%s" % oslot,
                dtype=next(iter(feeds.values())).dtype.name
                if feeds else "float32")
            outs[oslot] = [ov]
            fetch.append(ov)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(op_type)
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=dict(cfg.get("attrs", {})))

    place = fluid.TPUPlace(0) if dev == "tpu" else fluid.CPUPlace()
    exe = fluid.Executor(place)
    times = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(max(warmup, 1)):  # >=1: the first run compiles
            o = exe.run(main, feed=feeds, fetch_list=fetch[:1],
                        return_numpy=False)
        np.asarray(o[0])
        for _ in range(repeat):
            t0 = time.perf_counter()
            o = exe.run(main, feed=feeds, fetch_list=fetch[:1],
                        return_numpy=False)
            np.asarray(o[0])  # sync
            times.append((time.perf_counter() - t0) * 1e3)
    times = np.asarray(times)
    return {"op_type": op_type, "device": dev, "repeat": repeat,
            "mean_ms": round(float(times.mean()), 4),
            "p50_ms": round(float(np.median(times)), 4),
            "min_ms": round(float(times.min()), 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--device", default=None, choices=[None, "cpu", "tpu"])
    args = ap.parse_args()
    with open(args.config) as f:
        cfgs = json.load(f)
    if isinstance(cfgs, dict):
        cfgs = [cfgs]
    for cfg in cfgs:
        print(json.dumps(bench_op(cfg, device=args.device)))


if __name__ == "__main__":
    main()
