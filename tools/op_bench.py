"""Config-driven per-op micro-benchmark harness (parity:
paddle/fluid/operators/benchmark/op_tester.cc + op_tester_config.h).

Config: a JSON file (or inline dict) describing one or more ops::

    [
      {"op_type": "matmul",
       "inputs": {"X": {"dims": [64, 1024], "dtype": "fp32",
                        "initializer": "random"},
                  "Y": {"dims": [1024, 1024]}},
       "attrs": {"transpose_X": false},
       "repeat": 100, "device": "tpu"}
    ]

dtypes: fp32/fp64/int32/int64 (reference spellings accepted).
initializers: random | natural | zeros (op_tester_config.h:33-40).

Usage: python tools/op_bench.py <config.json> [--device cpu|tpu]
Prints one JSON line per op: {"op_type", "device", "repeat",
"mean_ms", "p50_ms", "min_ms"}.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPES = {"fp32": "float32", "float": "float32", "fp64": "float64",
           "double": "float64", "int32": "int32", "int": "int32",
           "int64": "int64", "long": "int64",
           "float32": "float32", "float64": "float64"}


def _make_input(spec, rng):
    dims = [int(d) for d in spec["dims"]]
    dtype = _DTYPES[spec.get("dtype", "fp32")]
    init = spec.get("initializer", "random")
    if init == "random":
        a = rng.rand(*dims) if dtype.startswith("float") else rng.randint(
            0, spec.get("max_value", 10), dims)
    elif init == "natural":
        a = np.arange(int(np.prod(dims))).reshape(dims)
    elif init == "zeros":
        a = np.zeros(dims)
    elif init == "file":
        a = np.load(spec["filename"])
    else:
        raise ValueError("unknown initializer %r" % init)
    return np.asarray(a, dtype)


def bench_op(cfg, device=None, repeat=None, warmup=None):
    import paddle_tpu as fluid
    from paddle_tpu.core.registry import get_op_def

    op_type = cfg["op_type"]
    opdef = get_op_def(op_type)
    repeat = int(repeat if repeat is not None else cfg.get("repeat", 50))
    warmup = int(warmup if warmup is not None else cfg.get("warmup", 5))
    dev = device or cfg.get("device", "cpu")

    rng = np.random.RandomState(int(cfg.get("seed", 0)))
    feeds = {}
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        inputs = {}
        for slot, spec in cfg.get("inputs", {}).items():
            name = "in_%s" % slot
            arr = _make_input(spec, rng)
            v = fluid.layers.data(name, shape=list(arr.shape[1:]),
                                  dtype=str(arr.dtype))
            feeds[name] = arr
            inputs[slot] = [v]
        block = main.global_block()
        outs = {}
        fetch = []
        for oslot in opdef.output_slots:
            ov = block.create_var(
                name="out_%s" % oslot,
                dtype=next(iter(feeds.values())).dtype.name
                if feeds else "float32")
            outs[oslot] = [ov]
            fetch.append(ov)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(op_type)
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=dict(cfg.get("attrs", {})))

    place = fluid.TPUPlace(0) if dev == "tpu" else fluid.CPUPlace()
    exe = fluid.Executor(place)
    times = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(max(warmup, 1)):  # >=1: the first run compiles
            o = exe.run(main, feed=feeds, fetch_list=fetch[:1],
                        return_numpy=False)
        np.asarray(o[0])
        for _ in range(repeat):
            t0 = time.perf_counter()
            o = exe.run(main, feed=feeds, fetch_list=fetch[:1],
                        return_numpy=False)
            np.asarray(o[0])  # sync
            times.append((time.perf_counter() - t0) * 1e3)
    times = np.asarray(times)
    return {"op_type": op_type, "device": dev, "repeat": repeat,
            "mean_ms": round(float(times.mean()), 4),
            "p50_ms": round(float(np.median(times)), 4),
            "min_ms": round(float(times.min()), 4)}


# kernel family -> the flag gating it (pallas_kernels/adoption.py KERNELS;
# fused_ln is flag-less/default-on and has no compare mode)
_PALLAS_FLAGS = {
    "conv_block": "FLAGS_use_pallas_conv_block",
    "fused_opt": "FLAGS_use_pallas_fused_opt",
    "embedding_bag": "FLAGS_use_pallas_embedding_bag",
    "layer_norm": "FLAGS_use_pallas_layer_norm",
}


def bench_pallas(cfg, device=None, save_probe=None, repeat=None,
                 warmup=None):
    """Back-to-back fallback vs Pallas-kernel run of one probe config
    (a normal bench_op config plus a "pallas_kernel" key naming the
    family).  The kernel leg runs with the family flag ON and an in-memory
    probe override, bypassing the disk probe gate — this IS the
    measurement that creates the probe row.  `save_probe`: directory to
    archive the row into (what adoption.py reads; BASELINE.md round-9
    protocol says commit it next to BENCH_*.json)."""
    import paddle_tpu as fluid
    from paddle_tpu.pallas_kernels import adoption

    kernel = cfg["pallas_kernel"]
    flag = _PALLAS_FLAGS[kernel]
    adoption.register_probe(kernel, float("inf"))
    fluid.flags.set_flags({flag: False})
    base = bench_op(cfg, device, repeat=repeat, warmup=warmup)
    fluid.flags.set_flags({flag: True})
    try:
        kern = bench_op(cfg, device, repeat=repeat, warmup=warmup)
    finally:
        fluid.flags.set_flags({flag: False})
    speedup = (base["mean_ms"] / kern["mean_ms"]) if kern["mean_ms"] else 0.0
    row = {
        "op_type": cfg["op_type"],
        "kernel": kernel,
        "device": kern["device"],
        "repeat": kern["repeat"],
        "fallback_mean_ms": base["mean_ms"],
        "kernel_mean_ms": kern["mean_ms"],
        "speedup": round(float(speedup), 4),
        # honesty bit: False means the kernel leg silently fell back
        # (ineligible shape / wrong backend) and the "speedup" compares
        # the fallback with itself — such a row must not be archived
        "kernel_engaged": kernel in adoption.active_kernels(),
    }
    if save_probe and row["kernel_engaged"]:
        os.makedirs(save_probe, exist_ok=True)
        path = os.path.join(save_probe, "%s.json" % kernel)
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")
        row["probe_file"] = path
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--device", default=None, choices=[None, "cpu", "tpu"])
    ap.add_argument("--pallas", action="store_true",
                    help="compare mode: fallback vs Pallas kernel per row "
                         "(rows need a 'pallas_kernel' key)")
    ap.add_argument("--save-probe", default=None, metavar="DIR",
                    help="with --pallas: append the probe JSON row to "
                         "DIR/<kernel>.json (the adoption-gate archive)")
    ap.add_argument("--repeat", type=int, default=None,
                    help="override every config row's repeat count")
    ap.add_argument("--warmup", type=int, default=None,
                    help="override every config row's warmup count")
    args = ap.parse_args()
    with open(args.config) as f:
        cfgs = json.load(f)
    if isinstance(cfgs, dict):
        cfgs = [cfgs]
    for cfg in cfgs:
        if args.pallas and cfg.get("pallas_kernel"):
            print(json.dumps(bench_pallas(cfg, device=args.device,
                                          save_probe=args.save_probe,
                                          repeat=args.repeat,
                                          warmup=args.warmup)))
        else:
            print(json.dumps(bench_op(cfg, device=args.device,
                                      repeat=args.repeat,
                                      warmup=args.warmup)))


if __name__ == "__main__":
    main()
