"""Capture a jax.profiler trace of the BERT bench step and print the
per-fusion device-time decomposition (the round-4/5 optimization loop's
measurement tool), plus an optional HBM footprint audit.

Usage: python tools/profile_bert_step.py [steps] [--steps N] [--audit]
                                         [--tiny] [--no-trace]

  --steps N    profiled steps (default 3; bare positional N still works)
  --audit      print the compiled step's memory_analysis with per-var
               attribution (core/memory_audit.py; same report as
               FLAGS_hbm_audit=1) before the timing trace
  --tiny       BERT_TINY config at batch 8 — a seconds-long CPU dry pass
               (the run_ci.sh --layout-smoke leg)
  --no-trace   skip the jax.profiler trace (audit/step-run only; the
               profiler needs a real TPU to produce XLA-Ops lanes)

Env: PROFILE_BATCH (default 192), PROFILE_TOP_OPS=1 for per-op listing.
"""

import os
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_args(argv):
    steps, audit, tiny, trace = 3, False, False, True
    it = iter(argv)
    for a in it:
        if a == "--steps":
            steps = int(next(it))
        elif a.startswith("--steps="):
            steps = int(a.split("=", 1)[1])
        elif a == "--audit":
            audit = True
        elif a == "--tiny":
            tiny = True
        elif a == "--no-trace":
            trace = False
        elif a.lstrip("-").isdigit():
            steps = int(a)
        else:
            raise SystemExit("unknown arg %r (see module docstring)" % a)
    return steps, audit, tiny, trace


def _print_telemetry(fluid):
    """Host-side step stats from the metrics registry — complements the
    device-time decomposition below (which only a real TPU trace gives)."""
    tel = fluid.telemetry
    if not tel.enabled():
        return
    snap = tel.snapshot()
    hists = snap.get("histograms", {})
    step = hists.get("executor_step_ms") or {}
    comp = hists.get("executor_compile_ms") or {}
    print("telemetry: steps=%d recompiles=%d cache_hits=%d "
          "compile_ms=%.1f step_ms p50=%.2f p90=%.2f p99=%.2f" % (
              tel.counter_total("executor_steps_total"),
              tel.counter_total("executor_cache_miss_total"),
              tel.counter_total("executor_cache_hit_total"),
              comp.get("sum", 0.0),
              step.get("p50", 0.0), step.get("p90", 0.0),
              step.get("p99", 0.0)))


def main():
    import jax
    import numpy as np

    steps, audit, tiny, do_trace = _parse_args(sys.argv[1:])

    # build the bench step exactly as bench_bert does, but hand-run it
    import paddle_tpu as fluid
    from paddle_tpu.models import bert as bert_model

    # host-side step stats ride the same run (core/telemetry.py); the
    # jax.profiler trace below still owns the device-time story
    fluid.set_flags({"FLAGS_telemetry": True})

    if tiny:
        batch, seq = 8, 32
        cfg = bert_model.BERT_TINY
    else:
        batch, seq = int(os.environ.get("PROFILE_BATCH", "192")), 128
        cfg = bert_model.BERT_BASE
    # AMP like bench_bert — the f32 and bf16-carry programs have entirely
    # different fusion structures, so profiling the wrong one misleads
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        inputs, seq_out = bert_model.bert_encoder(cfg, seq)
        mask_pos = fluid.layers.data("mask_pos", shape=[1], dtype="int64")
        mask_label = fluid.layers.data("mask_label", shape=[1],
                                       dtype="int64")
        flat = fluid.layers.reshape(seq_out, [-1, cfg.hidden])
        picked = fluid.layers.gather(flat, mask_pos)
        trans = fluid.layers.fc(picked, cfg.hidden, act="gelu")
        trans = fluid.layers.layer_norm(trans, begin_norm_axis=1)
        logits = fluid.layers.fc(trans, cfg.vocab_size)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, mask_label))
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        opt = fluid.contrib.mixed_precision.decorate(opt)
        opt.minimize(loss)
    if audit:
        # route the executor's first-run audit hook to stdout
        fluid.flags.set_flags({"FLAGS_hbm_audit": True})
        import logging as _logging

        _logging.basicConfig()
        _logging.getLogger().setLevel(_logging.WARNING)
    place = fluid.CPUPlace() if jax.default_backend() == "cpu" \
        else fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    n_mask = batch * int(seq * 0.15)
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq, 1)).astype("int64"),
        "pos_ids": np.tile(np.arange(seq)[None, :, None], (batch, 1, 1)).astype("int64"),
        "sent_ids": rng.randint(0, 2, (batch, seq, 1)).astype("int64"),
        "input_mask": np.ones((batch, seq, 1), "float32"),
        "mask_pos": rng.randint(0, batch * seq, (n_mask, 1)).astype("int64"),
        "mask_label": rng.randint(0, cfg.vocab_size, (n_mask, 1)).astype("int64"),
    }
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    with fluid.scope_guard(scope):
        exe.run(startup)

        def step():
            out, = exe.run(main_p, feed=feed, fetch_list=[loss],
                           return_numpy=False)
            return out

        for _ in range(max(min(3, steps), 1)):
            out = step()
        np.asarray(out)
        print("profile_bert_step: cfg=%s batch=%d seq=%d backend=%s "
              "loss=%.4f" % ("tiny" if tiny else "base", batch, seq,
                             jax.default_backend(),
                             float(np.asarray(out).reshape(-1)[0])))

        if not do_trace:
            for _ in range(steps):
                out = step()
            np.asarray(out)
            print("profile_bert_step: %d steps ran (trace skipped)" % steps)
            _print_telemetry(fluid)
            return

        from timeline import from_xplane

        tmpd = tempfile.mkdtemp(prefix="bert_prof_")
        with jax.profiler.trace(tmpd):
            for _ in range(steps):
                out = step()
            np.asarray(out)

    trace = from_xplane(tmpd)
    # device lane "XLA Ops"; async -start/-done spans cover their whole
    # in-flight window and OVERLAP compute, so they are not device time —
    # excluded from the totals
    buckets = defaultdict(float)
    total = 0.0
    for ev in trace["traceEvents"]:
        if "XLA Ops" not in ev["tid"]:
            continue
        name = ev["name"]
        if ("-start" in name or "-done" in name or "slice-s" in name
                or "copy-s" in name or "copy-d" in name):
            continue
        key = name.split(".")[0].split("(")[0].split("=")[0].strip()
        buckets[key] += ev["dur"] / 1e3  # ms
        total += ev["dur"] / 1e3
    _print_telemetry(fluid)
    print("total sync device ms over %d steps: %.1f (%.1f ms/step)" %
          (steps, total, total / steps))
    for k, v in sorted(buckets.items(), key=lambda kv: -kv[1])[:28]:
        print("  %-46s %8.2f ms/step" % (k, v / steps))
    if os.environ.get("PROFILE_TOP_OPS") == "1":
        per_op = defaultdict(float)
        for ev in trace["traceEvents"]:
            if "XLA Ops" not in ev["tid"]:
                continue
            name = ev["name"]
            if ("-start" in name or "-done" in name):
                continue
            per_op[name] += ev["dur"] / 1e3
        print("\ntop individual ops:")
        for k, v in sorted(per_op.items(), key=lambda kv: -kv[1])[:40]:
            print("  %9.3f ms/step  %s" % (v / steps, k))


if __name__ == "__main__":
    main()
