"""Shared measurement harness for the device probes.

The axon tunnel adds ~95-120 ms of host round-trip to every dispatch+fetch
and `block_until_ready` does not actually wait on this platform, so every
probe must: chain repetitions inside ONE jit call (lax.scan with
lax.optimization_barrier on loop-invariant operands — XLA otherwise elides
work via slice-of-dot/slice-of-conv/hoisted algebra), sync via a host
fetch of a scalar that data-depends on all outputs, and subtract the
separately-measured RTT.  The round-2 roofline in BASELINE.md was wrong
precisely because its harness skipped these steps.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args, r=5):
    """Median wall time of r calls of jit(fn)(*args), host-fetch synced on
    the first element of the result tuple."""
    f = jax.jit(fn)
    o = f(*args)
    np.asarray(o[0])
    ts = []
    for _ in range(r):
        t0 = time.perf_counter()
        o = f(*args)
        np.asarray(o[0])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def tunnel_rtt(r=9):
    """Median dispatch+fetch round-trip for a trivial computation."""
    f = jax.jit(lambda s: s + 1.0)
    s = jnp.float32(0.0)
    np.asarray(f(s))
    ts = []
    for _ in range(r):
        t0 = time.perf_counter()
        np.asarray(f(s))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))
