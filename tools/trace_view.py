"""Merge per-process tracing JSONL into one Chrome/Perfetto trace.json.

Every traced process writes ``trace-<pid>.jsonl`` under
``FLAGS_telemetry_dir`` (core/tracing.py); a multi-process run — fleet
replicas + client, launch.py trainers/pservers — therefore leaves one
file per process.  This tool merges them into a single chrome-trace
document:

- each process is a named track (the ``proc`` header record carries the
  name set via ``tracing.set_process_name``; threads become sub-tracks)
- ``span`` records become ``ph:"X"`` slices, ``inst``/``note`` records
  become instant markers (flight-recorder ``flightrec-*.json`` dumps are
  folded in as process-scoped instants so a postmortem shows up on the
  dead replica's track)
- a parent->child span edge or a span link whose two ends live in
  DIFFERENT processes becomes a flow arrow (``ph:"s"``/``"f"``) keyed by
  trace_id, so one request's client.infer -> serving.admission -> ... ->
  serving.reply_publish chain reads as one connected line across tracks
- a ``link``-kind edge between two span trees of the SAME process also
  becomes an arrow (same-process parent edges stay implicit in the slice
  nesting): the elastic re-quorum's restore phase links the
  ``checkpoint.save``/``checkpoint.restore`` tree that produced its
  state, so recovery reads as checkpoint I/O flowing into the re-quorum

Usage:
    python tools/trace_view.py --telemetry_dir /tmp/tel --out trace.json
    python tools/trace_view.py --telemetry_dir ... --out ... --require-flow

``--require-flow`` exits non-zero unless at least one cross-process flow
was emitted (the --trace-smoke CI gate).  Open the output in
https://ui.perfetto.dev or chrome://tracing.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from timeline import track_meta  # noqa: E402


def read_jsonl(path):
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a killed process
    return out


def load_dir(telemetry_dir):
    """-> list of (pid, proc_name, records) per trace-*.jsonl, with any
    flightrec-*.json records folded into the matching process (or their
    own synthetic process when no JSONL exists for that pid)."""
    procs = {}
    for path in sorted(glob.glob(os.path.join(telemetry_dir,
                                              "trace-*.jsonl"))):
        # include a rotated predecessor so a long soak still merges
        recs = read_jsonl(path + ".1") if os.path.exists(path + ".1") \
            else []
        recs += read_jsonl(path)
        pid = int(os.path.basename(path)[len("trace-"):-len(".jsonl")])
        name = "pid-%d" % pid
        for r in recs:
            if r.get("t") == "proc" and r.get("name"):
                name = r["name"]
        procs[pid] = (name, recs)
    for path in sorted(glob.glob(os.path.join(telemetry_dir,
                                              "flightrec-*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError:
            continue
        pid = int(doc.get("proc", {}).get("pid", 0) or
                  os.path.basename(path)[len("flightrec-"):-len(".json")])
        name, recs = procs.get(pid) or (
            doc.get("proc", {}).get("name") or "pid-%d" % pid, [])
        recs = list(recs)
        recs.append({"t": "note", "kind": "flightrec",
                     "ts": doc.get("dumped_at", 0),
                     "reason": doc.get("reason", "?"), "thr": "flightrec"})
        # only add ring records the JSONL does not already carry (a live
        # process logs both; a SIGKILLed one may only have the dump)
        seen = {(r.get("t"), r.get("sid"), r.get("ts")) for r in recs}
        for r in doc.get("records", []):
            if (r.get("t"), r.get("sid"), r.get("ts")) not in seen:
                recs.append(r)
        procs[pid] = (name, recs)
    return [(pid, nm, rc) for pid, (nm, rc) in sorted(procs.items())]


# chrome://tracing reserved color names for span families whose phases
# should be tellable apart at a glance: the speculative-decode children
# (draft work yellow-ish, the target verify step green, so accept/reject
# economics show up visually) and the checkpoint tree (the foreground
# D2H snapshot + save stall vs the background write — the async-save
# contract is precisely that the yellow I/O slice leaves the step track)
_SPAN_COLORS = {"serving.draft": "thread_state_iowait",
                "serving.draft_ingest": "thread_state_iowait",
                "serving.verify": "thread_state_running",
                "executor.snapshot": "thread_state_runnable",
                "checkpoint.save": "rail_response",
                "checkpoint.write": "thread_state_iowait",
                "checkpoint.restore": "rail_load"}


def merge(procs):
    """-> (chrome trace dict, number of cross-process flows)."""
    events = []
    span_home = {}   # span_id -> (pid, tid, ts_us, name)
    edges = []       # (child_pid, child_tid, child_ts, trace_id,
                     #  parent_sid, child_sid, kind)
    tid_maps = {}
    for sort, (pid, name, recs) in enumerate(procs):
        events.extend(track_meta(pid, name, sort_index=sort))
        tids = tid_maps.setdefault(pid, {})

        def tid_of(thr):
            if thr not in tids:
                tids[thr] = len(tids) + 1
                events.extend(track_meta(pid, name, tid=tids[thr],
                                         thread_name=thr)[1:])
            return tids[thr]

        for r in recs:
            t = r.get("t")
            ts = r.get("ts", 0)
            tid = tid_of(r.get("thr", "main"))
            if t == "span":
                args = dict(r.get("attrs") or {})
                args["trace_id"] = r.get("tid")
                args["span_id"] = r.get("sid")
                if r.get("parent"):
                    args["parent_id"] = r["parent"]
                ev = {"name": r.get("name", "?"), "ph": "X",
                      "pid": pid, "tid": tid, "ts": ts,
                      "dur": max(r.get("dur", 0), 1),
                      "cat": "span", "args": args}
                cname = _SPAN_COLORS.get(ev["name"])
                if cname:
                    ev["cname"] = cname
                events.append(ev)
                span_home[r.get("sid")] = (pid, tid, ts,
                                           r.get("name", "?"))
                if r.get("parent"):
                    edges.append((pid, tid, ts, r.get("tid"),
                                  r["parent"], r.get("sid"), "parent"))
                for ltid, lsid in r.get("links") or []:
                    # link arrow points batch -> linked request: start at
                    # the LINKED span, finish at this one
                    edges.append((pid, tid, ts, ltid, lsid,
                                  r.get("sid"), "link"))
            elif t in ("inst", "note"):
                nm = r.get("name") if t == "inst" else \
                    "note:%s" % r.get("kind", "?")
                args = {k: v for k, v in r.items()
                        if k not in ("t", "ts", "thr", "name")}
                events.append({"name": nm, "ph": "i", "pid": pid,
                               "tid": tid, "ts": ts,
                               "s": "t" if t == "inst" else "p",
                               "cat": t, "args": args})
    flows = local_flows = 0
    for cpid, ctid, cts, trace_id, psid, csid, kind in edges:
        home = span_home.get(psid)
        if home is None:
            continue
        if home[0] == cpid and kind != "link":
            # same-process parent edge: the slice nesting already shows it
            continue
        ppid, ptid, pts, pname = home
        fid = "%s:%s" % (trace_id, csid)
        events.append({"name": "trace", "cat": "flow", "ph": "s",
                       "id": fid, "pid": ppid, "tid": ptid,
                       "ts": pts + 1})
        events.append({"name": "trace", "cat": "flow", "ph": "f",
                       "bp": "e", "id": fid, "pid": cpid, "tid": ctid,
                       "ts": max(cts + 1, pts + 2)})
        if home[0] == cpid:
            # link between two span TREES of one process — e.g. the
            # elastic restore phase pointing back at the checkpoint
            # save/restore tree that produced its state; without the
            # arrow they read as unrelated tracks
            local_flows += 1
        else:
            flows += 1
    events.sort(key=lambda e: (e.get("ts", 0), e.get("ph") != "M"))
    return {"traceEvents": events,
            "displayTimeUnit": "ms"}, flows, local_flows


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-process trace-*.jsonl into trace.json")
    ap.add_argument("--telemetry_dir", required=True,
                    help="FLAGS_telemetry_dir of the traced run")
    ap.add_argument("--out", required=True, help="output trace.json path")
    ap.add_argument("--require-flow", action="store_true",
                    help="exit 1 unless >=1 cross-process flow merged")
    args = ap.parse_args(argv)
    procs = load_dir(args.telemetry_dir)
    if not procs:
        print("no trace-*.jsonl under %s" % args.telemetry_dir,
              file=sys.stderr)
        return 1
    trace, flows, local_flows = merge(procs)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    print("merged %d processes, %d events, %d cross-process + %d "
          "same-process link flows -> %s"
          % (len(procs), len(trace["traceEvents"]), flows, local_flows,
             args.out))
    if args.require_flow and flows == 0:
        print("--require-flow: no cross-process flow found",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
