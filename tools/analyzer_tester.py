"""Per-model inference regression tester (reference
inference/tests/api/analyzer_*_tester.cc + tester_helper.h): loads a saved
inference model through AnalysisPredictor, measures latency over --repeat
runs, and checks accuracy against a golden outputs file.

Usage:
    python tools/analyzer_tester.py --model_dir DIR --inputs inputs.npz \
        [--golden golden.npz] [--capture] [--repeat 100] [--warmup 10] \
        [--atol 1e-5] [--cache_dir DIR] [--use_tpu]

  --capture writes the current outputs as the new golden.
  Exit code 0 = pass; prints one JSON line with latency stats + max|diff|.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_model(model_dir, inputs, repeat=50, warmup=5, use_tpu=False,
              cache_dir=None):
    import paddle_tpu as fluid
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    config = AnalysisConfig(model_dir)
    if use_tpu:
        config.enable_use_tpu()
    else:
        config.disable_gpu()
    if cache_dir:
        config.set_optim_cache_dir(cache_dir)
    predictor = create_paddle_predictor(config)

    names = predictor.get_input_names()
    for n in names:
        t = predictor.get_input_tensor(n)
        t.copy_from_cpu(inputs[n])

    predictor.zero_copy_run()  # compile
    for _ in range(warmup):
        predictor.zero_copy_run()
    lats = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        predictor.zero_copy_run()
        # pull one output: latency includes device->host like the
        # reference testers' PaddleTensor copies
        out0 = predictor.get_output_tensor(
            predictor.get_output_names()[0]).copy_to_cpu()
        lats.append((time.perf_counter() - t0) * 1000)
    outs = {n: predictor.get_output_tensor(n).copy_to_cpu()
            for n in predictor.get_output_names()}
    lats = np.array(lats)
    stats = {
        "avg_ms": float(lats.mean()),
        "p50_ms": float(np.percentile(lats, 50)),
        "p99_ms": float(np.percentile(lats, 99)),
        "repeat": repeat,
    }
    return outs, stats


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model_dir", required=True)
    ap.add_argument("--inputs", required=True, help=".npz of input arrays")
    ap.add_argument("--golden", default=None, help=".npz of expected outputs")
    ap.add_argument("--capture", action="store_true",
                    help="write outputs to --golden instead of comparing")
    ap.add_argument("--repeat", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--atol", type=float, default=1e-5)
    ap.add_argument("--rtol", type=float, default=1e-4)
    ap.add_argument("--use_tpu", action="store_true")
    ap.add_argument("--cache_dir", default=None)
    args = ap.parse_args(argv)

    inputs = dict(np.load(args.inputs, allow_pickle=False))
    outs, stats = run_model(args.model_dir, inputs, args.repeat, args.warmup,
                            args.use_tpu, args.cache_dir)

    max_diff = None
    status = "ok"
    if args.capture:
        if not args.golden:
            ap.error("--capture needs --golden")
        np.savez(args.golden, **outs)
    elif args.golden:
        golden = dict(np.load(args.golden, allow_pickle=False))
        max_diff = 0.0
        for n, want in golden.items():
            got = outs[n]
            d = float(np.max(np.abs(np.asarray(got, "float64")
                                    - np.asarray(want, "float64"))))
            max_diff = max(max_diff, d)
            if not np.allclose(got, want, atol=args.atol, rtol=args.rtol):
                status = "accuracy_fail"
    print(json.dumps({"model": args.model_dir, "status": status,
                      "max_abs_diff": max_diff, **stats}))
    return 0 if status == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
