"""Continuous-batching inference server entry point (serving/ subsystem).

Usage:
    # single replica, two models, AOT-compile the buckets, serve
    python tools/serve.py --model fc=/path/to/model \
        --model bert=/path/to/bert --port 9000 --buckets 1,4,16 \
        --cache-dir /tmp/cc

    # CI-style: compile every (model, bucket) into the cache and exit
    python tools/serve.py --model fc=/path --prewarm-only --cache-dir /tmp/cc

    # elastic fleet of N replicas: run once per replica with the SAME
    # --fleet list; the coordinator (lowest live rank) maintains
    # --endpoints-file for client failover
    python tools/serve.py --model fc=/path --rank 0 \
        --fleet 127.0.0.1:9000,127.0.0.1:9001 \
        --endpoints-file /tmp/eps.json

    # elastic fleet + autoscaling: the coordinator watches queue depth /
    # shed rate and forks prewarmed standbys into dead --fleet slots on
    # sustained pressure, retires the highest rank on sustained idle
    python tools/serve.py --model fc=/path --rank 0 \
        --fleet 127.0.0.1:9000,127.0.0.1:9001 --cache-dir /tmp/cc \
        --endpoints-file /tmp/eps.json --autoscale --max-replicas 2

    # disaggregated prefill/decode fleet: role column parallels --fleet;
    # prefill replicas stream sealed KV blocks to decode replicas and
    # clients route __generate__ by the published roles
    python tools/serve.py --model toy=/tmp/dec --rank 0 \
        --fleet 127.0.0.1:9000,127.0.0.1:9001,127.0.0.1:9002,127.0.0.1:9003 \
        --roles prefill,prefill,decode,decode --endpoints-file /tmp/eps.json

    # helper for smoke tests: save a tiny fc inference model and exit
    python tools/serve.py --save-demo-model /tmp/model

    # autoregressive decode serving: a --model DIR holding a
    # save_decoder() bundle (decoder.json + params.npz) is routed to the
    # paged-KV DecodeEngine instead; helper to create one:
    python tools/serve.py --save-demo-decoder /tmp/dec
    python tools/serve.py --model toy=/tmp/dec --decode-buckets 4,8

The prewarm manifest prints one JSON line (PREWARM {...}) so harnesses
can assert every bucket exists before traffic starts; "READY port=N" on
stdout marks the server accepting requests.
"""

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def save_demo_model(dirname, in_dim=8, out_dim=4):
    """Tiny fc softmax model via save_inference_model (smoke tests)."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[in_dim])
        h = fluid.layers.fc(x, 16, act="relu")
        out = fluid.layers.fc(h, out_dim, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=main)
    return dirname


def save_demo_decoder(dirname, vocab=31, layers=2, heads=2, head_dim=8,
                      max_seq=48, seed=7):
    """Tiny decode model via serving.decode_model.save_decoder, bundled
    with a first-layer-truncation draft so FLAGS_speculative_k > 0 can
    speculate out of the box."""
    from paddle_tpu.serving.decode_model import (DecoderConfig,
                                                 init_decoder_params,
                                                 save_decoder,
                                                 truncate_decoder)

    cfg = DecoderConfig(vocab=vocab, layers=layers, heads=heads,
                        head_dim=head_dim, max_seq=max_seq)
    params = init_decoder_params(cfg, seed=seed)
    return save_decoder(dirname, cfg, params,
                        draft=truncate_decoder(cfg, params, layers=1))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", action="append", default=[],
                    metavar="NAME=DIR",
                    help="register a model (repeatable): serving name = "
                    "save_inference_model directory")
    ap.add_argument("--port", type=int, default=0,
                    help="RPC port (0 = ephemeral; printed on READY)")
    ap.add_argument("--buckets", default=None,
                    help="batch buckets, e.g. 1,4,16,64 "
                    "(default FLAGS_serving_buckets)")
    ap.add_argument("--cache-dir", default=None,
                    help="FLAGS_compile_cache_dir for AOT bucket artifacts")
    ap.add_argument("--prewarm-only", action="store_true",
                    help="compile every (model, bucket), print the "
                    "manifest, exit")
    ap.add_argument("--rank", type=int, default=0,
                    help="this replica's rank in --fleet")
    ap.add_argument("--fleet", default=None,
                    help="comma list of ALL replica endpoints (host:port); "
                    "enables fleet membership")
    ap.add_argument("--endpoints-file", default=None,
                    help="coordinator-maintained live-endpoints file "
                    "(client failover)")
    ap.add_argument("--save-demo-model", metavar="DIR", default=None,
                    help="write a tiny fc inference model to DIR and exit")
    ap.add_argument("--save-demo-decoder", metavar="DIR", default=None,
                    help="write a tiny autoregressive decoder to DIR "
                    "and exit")
    ap.add_argument("--decode-buckets", default=None,
                    help="decode lane buckets, e.g. 4,8 "
                    "(default FLAGS_serving_decode_buckets)")
    ap.add_argument("--decode-mode", default=None,
                    choices=("token", "request"),
                    help="token-level continuous batching (default) or "
                    "the request-level baseline")
    ap.add_argument("--kv-blocks", type=int, default=None,
                    help="paged KV pool size in blocks "
                    "(default FLAGS_kv_cache_blocks / HBM budget)")
    ap.add_argument("--speculative-k", type=int, default=None,
                    help="draft-model speculation depth for decode "
                    "models with a bundled draft (default "
                    "FLAGS_speculative_k; 0 = off)")
    ap.add_argument("--role", default=None,
                    choices=("serve", "prefill", "decode"),
                    help="disaggregated serving role for THIS replica "
                    "(default: this rank's --roles column entry, else "
                    "monolith \"serve\")")
    ap.add_argument("--roles", default=None,
                    help="comma role column parallel to --fleet "
                    "(serve|prefill|decode per slot); the coordinator "
                    "publishes it in the endpoints file so clients "
                    "route __generate__ to prefill replicas")
    ap.add_argument("--decode-peers", default=None,
                    help="comma list of decode-role endpoints a prefill "
                    "replica streams sealed KV blocks to when no fleet "
                    "role column is in play (tests / static pairs)")
    ap.add_argument("--autoscale", action="store_true",
                    help="coordinator only: watch queue depth / shed "
                    "rate and launch prewarmed standby replicas into "
                    "dead --fleet slots on sustained pressure, drain + "
                    "retire the highest rank on sustained idle")
    ap.add_argument("--min-replicas", type=int, default=None,
                    help="autoscaler floor (default "
                    "FLAGS_serving_min_replicas)")
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="autoscaler ceiling (default "
                    "FLAGS_serving_max_replicas; also clamped by the "
                    "--fleet slot count)")
    args = ap.parse_args(argv)

    if args.save_demo_model:
        print("saved demo model:", save_demo_model(args.save_demo_model))
        return 0
    if args.save_demo_decoder:
        print("saved demo decoder:",
              save_demo_decoder(args.save_demo_decoder))
        return 0

    import paddle_tpu as fluid
    from paddle_tpu.core import tracing
    from paddle_tpu.serving import ServingEngine, ServingFleet, ServingServer

    if args.cache_dir:
        fluid.set_flags({"FLAGS_compile_cache_dir": args.cache_dir})
    # names this replica's track in the merged trace_view.py output
    tracing.set_process_name("serving-replica-%d" % args.rank)
    if not args.model:
        ap.error("at least one --model NAME=DIR is required")

    from paddle_tpu.serving import DecodeEngine
    from paddle_tpu.serving.decode_model import is_decoder_dir

    engine = ServingEngine(buckets=args.buckets)
    decode_engine = None
    for spec in args.model:
        name, _, dirname = spec.partition("=")
        if not dirname:
            ap.error("--model wants NAME=DIR, got %r" % spec)
        if is_decoder_dir(dirname):
            if decode_engine is None:
                decode_engine = DecodeEngine(buckets=args.decode_buckets,
                                             mode=args.decode_mode)
            decode_engine.add_model(name, dirname,
                                    kv_blocks=args.kv_blocks,
                                    speculative_k=args.speculative_k)
        else:
            engine.add_model(name, dirname)

    manifest = engine.prewarm()
    if decode_engine is not None:
        manifest.update(decode_engine.prewarm())
    print("PREWARM " + json.dumps(manifest), flush=True)
    if args.prewarm_only:
        return 0

    if args.fleet:
        endpoints = [e.strip() for e in args.fleet.split(",") if e.strip()]
        port = args.port or int(endpoints[args.rank].rsplit(":", 1)[1])
    else:
        endpoints, port = None, args.port

    roles = None
    if args.roles:
        roles = [r.strip() for r in args.roles.split(",") if r.strip()]
        if endpoints is None or len(roles) != len(endpoints):
            ap.error("--roles must parallel --fleet")
    role = args.role or (roles[args.rank] if roles else None)
    decode_peers = [e.strip() for e in (args.decode_peers or "").split(",")
                    if e.strip()]
    server = ServingServer(engine, port=port, rank=args.rank,
                           decode_engine=decode_engine, role=role,
                           decode_peers=decode_peers).start()
    fleet = None
    if endpoints:
        fleet = ServingFleet(args.rank, endpoints, server,
                             endpoints_file=args.endpoints_file,
                             roles=roles).start()

    # rollout controller: serves __rollout_ctl__ admin commands and runs
    # the canary metrics gate (auto-rollback); with a fleet, state
    # changes broadcast to peers and ride the epoch-bumped endpoints file
    from paddle_tpu.serving import RolloutController

    server.rollout = RolloutController(server, fleet).start()

    # fleet observability plane (PR 18): scrape every live replica each
    # tick, merge histograms / window rates / evaluate burn-rate SLOs,
    # republish the merged doc under __fleet__ on the coordinator.  The
    # autoscaler closures below prefer its fleet-windowed view.
    monitor = None
    from paddle_tpu.core import telemetry as _tmon

    if _tmon.enabled() and (fleet is not None or args.endpoints_file):
        from paddle_tpu.serving import FleetMonitor

        monitor = FleetMonitor(server=server, fleet=fleet,
                               endpoints_file=args.endpoints_file).start()
    server.fleetmon = monitor

    done = threading.Event()
    # a drained __retire__ order exits the process like a SIGTERM would
    server.on_retire = done.set

    scalers = []
    if args.autoscale and fleet is not None:
        from paddle_tpu import flags as _flags
        from paddle_tpu.core import telemetry as _tm
        from paddle_tpu.serving import AutoScaler

        def child_argv(rank):
            """Re-exec this invocation for a standby slot (the child
            shares --cache-dir, so its prewarm is restore-dominated);
            the child never autoscales itself and takes its role from
            its --roles column slot."""
            out, it = [sys.executable, os.path.abspath(__file__)], \
                iter(sys.argv[1:])
            for a in it:
                if a == "--autoscale":
                    continue
                if a in ("--rank", "--min-replicas", "--max-replicas",
                         "--role"):
                    next(it, None)
                    continue
                out.append(a)
            return out + ["--rank", str(rank)]

        def local_depth():
            depth = len(engine._queue)
            if decode_engine is not None:
                depth += len(decode_engine._waiting)
            return depth

        def scale_up_for(want_role):
            def fn():
                import subprocess

                if not fleet.is_coordinator():
                    return
                dead = [r for r in range(len(fleet.endpoints))
                        if r not in fleet.live
                        and (want_role is None
                             or fleet.role_of(r) == want_role)]
                if not dead:
                    return
                rank = dead[0]
                fleet.notice_relaunch(rank)
                subprocess.Popen(child_argv(rank), start_new_session=True)
            return fn

        def scale_down_for(want_role):
            def fn():
                if not fleet.is_coordinator():
                    return
                cands = [r for r in sorted(fleet.live)
                         if r != fleet.rank
                         and (want_role is None
                              or fleet.role_of(r) == want_role)]
                if cands:
                    fleet.retire(cands[-1])
            return fn

        if roles is None:
            def metrics():
                # fleet-windowed view when the monitor has a doc (queue
                # depth summed across replicas, shed/s over the rate
                # window); local instants only until its first tick
                if monitor is not None:
                    m = monitor.autoscale_metrics()
                    if m is not None and m.get("replicas_up"):
                        return m
                return {"queue_depth": local_depth(),
                        "shed_total": _tm.counter_total(
                            "serving_shed_total")}

            scalers.append(AutoScaler(
                metrics, scale_up_for(None), scale_down_for(None),
                replicas_fn=lambda: len(fleet.live),
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas).start())
        else:
            # disaggregated fleet: one controller per role, each with a
            # role-specific pressure signal — prefill chases admission
            # queue depth (TTFT pressure), decode chases KV-pool
            # occupancy (ITL pressure).  Peer replicas are scraped over
            # __metrics__; this replica contributes locally.
            def role_metrics(want_role):
                def fn():
                    if monitor is not None:
                        m = monitor.autoscale_metrics(want_role)
                        if m is not None and m.get("replicas_up"):
                            return m
                    depth = occ = shed = 0.0
                    for ep in fleet.live_role_endpoints(want_role):
                        if ep == fleet.endpoints[fleet.rank]:
                            continue
                        try:
                            snap = _tm.scrape(ep, timeout=2.0)
                        except Exception:
                            continue
                        g = snap.get("gauges", {})
                        depth += max(
                            (v for k, v in g.items()
                             if k.startswith("serving_queue_depth")),
                            default=0.0)
                        occ = max(occ, max(
                            (v for k, v in g.items()
                             if k.startswith("kv_pool_occupancy")),
                            default=0.0))
                        shed += sum(
                            v for k, v in
                            snap.get("counters", {}).items()
                            if k.startswith("serving_shed_total"))
                    if fleet.role_of(fleet.rank) == want_role:
                        depth += local_depth()
                        shed += _tm.counter_total("serving_shed_total")
                        if decode_engine is not None:
                            for m in decode_engine._models.values():
                                alloc = m.cache.allocator
                                occ = max(occ, alloc.in_use /
                                          (float(alloc.capacity) or 1.0))
                    return {"queue_depth": depth, "shed_total": shed,
                            "kv_occupancy": occ}
                return fn

            up_depth = float(_flags.flag("serving_scale_up_depth"))

            def prefill_pressure(m):
                d = float(m.get("queue_depth", 0.0))
                return d >= up_depth, d <= 0.0

            def decode_pressure(m):
                occ = float(m.get("kv_occupancy", 0.0))
                return occ >= 0.85, occ <= 0.30

            for want_role, pfn in (("prefill", prefill_pressure),
                                   ("decode", decode_pressure)):
                if want_role not in roles:
                    continue
                scalers.append(AutoScaler(
                    role_metrics(want_role), scale_up_for(want_role),
                    scale_down_for(want_role),
                    replicas_fn=(lambda wr=want_role:
                                 len(fleet.live_role_ranks(wr))),
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas,
                    pressure_fn=pfn).start())

    print("READY port=%d" % server.port, flush=True)

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    for scaler in scalers:
        scaler.stop()
    if monitor is not None:
        monitor.stop()
    if fleet is not None:
        fleet.stop()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
