"""Live top-style dashboard over the serving fleet's metrics plane.

Usage:
    # aggregate locally: re-read the endpoints file each refresh, scrape
    # every live replica, merge (serving/fleetmon.py FleetMonitor)
    python tools/fleet_top.py --endpoints-file /tmp/eps.json

    # static endpoint list (no fleet file, e.g. a test rig)
    python tools/fleet_top.py --endpoints 127.0.0.1:9000,127.0.0.1:9001

    # read the coordinator's already-merged __fleet__ doc (one GET
    # instead of N scrapes; needs a running FleetMonitor over there)
    python tools/fleet_top.py --scrape 127.0.0.1:9000

    # scripting: one sample, machine-readable
    python tools/fleet_top.py --endpoints 127.0.0.1:9000 --once --json

Each refresh shows one row per replica (role, queue depth, batch fill,
KV occupancy, prefix hit rate, per-phase p99s) over fleet-level lines:
goodput vs raw throughput, windowed shed/token rates, and every SLO
rule's multi-window burn rate with its FIRING/ok state.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_monitor = [None]                      # kept across refreshes: the ring


def collect(args):
    """One fleet doc: either the coordinator's published ``__fleet__``
    aggregate, or a local FleetMonitor tick (the monitor persists
    between refreshes so windowed rates/percentiles have history)."""
    if args.endpoint:
        from paddle_tpu import telemetry
        from paddle_tpu.serving.fleetmon import FLEET_RPC_KEY

        return telemetry.scrape(args.endpoint, timeout=args.timeout,
                                key=FLEET_RPC_KEY)
    if _monitor[0] is None:
        from paddle_tpu.serving.fleetmon import FleetMonitor

        eps = [e.strip() for e in (args.endpoints or "").split(",")
               if e.strip()] or None
        _monitor[0] = FleetMonitor(endpoints_file=args.endpoints_file,
                                   endpoints=eps)
    return _monitor[0].tick()


def render(doc, out=sys.stdout, clear=False):
    if clear:
        out.write("\x1b[2J\x1b[H")
    out.write("fleet_top  t=%.1f  epoch=%s  replicas up=%s  "
              "(refresh data: %gs rate window)\n"
              % (doc.get("t", 0.0), doc.get("epoch", "?"),
                 doc.get("replicas_up", "?"),
                 doc.get("rate_window_s", 0.0)))
    out.write("%-22s %-8s %-3s %5s %5s %5s %5s %9s %9s %9s\n"
              % ("ENDPOINT", "ROLE", "UP", "QD", "FILL", "KV%", "HIT%",
                 "SRV p99", "TTFT p99", "ITL p99"))
    for r in doc.get("replicas", []):
        p99 = r.get("p99_ms", {})
        out.write("%-22s %-8s %-3s %5g %5.2f %5.1f %5.1f %9g %9g %9g\n"
                  % (r.get("endpoint", "?"), r.get("role", "?"),
                     "y" if r.get("up") else "N",
                     r.get("queue_depth", 0.0),
                     r.get("batch_fill_p50", 0.0),
                     100.0 * r.get("kv_occupancy", 0.0),
                     100.0 * r.get("prefix_hit_rate", 0.0),
                     p99.get("server_ms", 0.0),
                     p99.get("ttft_ms", 0.0),
                     p99.get("itl_ms", 0.0)))
    gp = doc.get("goodput", {})
    if gp:
        out.write("goodput  %.1f/%.1f replies/s met deadline   "
                  "%.1f/%.1f tokens/s   missed %.2f/s\n"
                  % (gp.get("replies_per_s", 0.0),
                     gp.get("raw_replies_per_s", 0.0),
                     gp.get("tokens_per_s", 0.0),
                     gp.get("raw_tokens_per_s", 0.0),
                     gp.get("missed_per_s", 0.0)))
    rates = doc.get("rates", {})
    shed = sum(v for k, v in rates.items()
               if k.split("{", 1)[0] == "serving_shed_total")
    if shed:
        out.write("shedding %.2f/s\n" % shed)
    for s in doc.get("slo", []):
        out.write("slo %-14s p%d(%s) %gms/%gms obj  burn fast=%.2f "
                  "slow=%.2f  [%s]\n"
                  % (s["name"], round(s["quantile"] * 100), s["metric"],
                     s["p_fast_ms"], s["objective_ms"], s["burn_fast"],
                     s["burn_slow"],
                     "FIRING" if s["active"] else "ok"))


def main(argv=None):
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--endpoints-file",
                     help="fleet endpoints file (re-read each refresh; "
                     "membership changes appear live)")
    src.add_argument("--endpoints",
                     help="comma list of replica endpoints (static rig)")
    src.add_argument("--scrape", dest="endpoint",
                     help="coordinator HOST:PORT — GET the published "
                     "__fleet__ aggregate instead of scraping N replicas")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (live mode)")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-scrape RPC deadline in seconds")
    ap.add_argument("--once", action="store_true",
                    help="one sample then exit (no screen clearing)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw fleet doc as JSON (scripting)")
    args = ap.parse_args(argv)

    while True:
        doc = collect(args)
        if args.as_json:
            json.dump(doc, sys.stdout, indent=1)
            sys.stdout.write("\n")
        else:
            render(doc, clear=not args.once)
        sys.stdout.flush()
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
