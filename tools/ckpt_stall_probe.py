"""Measure the training-loop cost of checkpointing: mean step time vs the
foreground stall of ``CheckpointManager.save``.

Trains a small fc regression for ``--steps`` steps, saving every
``--save-every`` steps, then reports per-save foreground stall
(``checkpoint_save_stall_ms``) and background write time
(``checkpoint_write_ms``) from telemetry next to the measured step time.
With ``--assert-stall-frac F`` the probe exits nonzero unless the mean
save stall is under ``F`` of the mean step time — the CI ``--ckpt-smoke``
leg runs it with the BASELINE validity bar (0.05, i.e. a save may not
cost more than 5% of a step).

    python tools/ckpt_stall_probe.py --steps 30 --save-every 2 \
        --assert-stall-frac 0.05 --out probe.json
    python tools/ckpt_stall_probe.py --sync ...   # blocking-save baseline
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import telemetry as _tm


def build_net(hidden):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 17
    startup.random_seed = 17
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[64])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(x, hidden, act="relu",
                            param_attr=fluid.ParamAttr(name="pr_w1"),
                            bias_attr=fluid.ParamAttr(name="pr_b1"))
        h = fluid.layers.fc(h, hidden, act="relu",
                            param_attr=fluid.ParamAttr(name="pr_w2"),
                            bias_attr=fluid.ParamAttr(name="pr_b2"))
        pred = fluid.layers.fc(h, 1,
                               param_attr=fluid.ParamAttr(name="pr_w3"),
                               bias_attr=fluid.ParamAttr(name="pr_b3"))
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    return main, startup, loss


def _hist(snap, name):
    h = snap.get("histograms", {}).get(name)
    return h if h else {"count": 0, "sum": 0.0, "p50": 0.0, "p90": 0.0,
                        "p99": 0.0}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--sync", action="store_true",
                    help="blocking saves (the pre-async baseline)")
    ap.add_argument("--ckpt-dir", type=str, default=None,
                    help="checkpoint directory (default: a temp dir)")
    ap.add_argument("--assert-stall-frac", type=float, default=None,
                    help="fail unless mean save stall < FRAC * mean step")
    ap.add_argument("--out", type=str, default=None,
                    help="write the result record as JSON")
    args = ap.parse_args(argv)

    fluid.set_flags({"FLAGS_telemetry": True})
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="ckpt_probe_")

    from paddle_tpu.io import CheckpointManager

    main_prog, startup, loss = build_net(args.hidden)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    mgr = CheckpointManager(ckpt_dir, save_interval=args.save_every,
                            max_num=2, async_save=not args.sync)

    rng = np.random.RandomState(7)
    xs = rng.randn(args.batch, 64).astype("f")
    ys = rng.randn(args.batch, 1).astype("f")

    step_ms = []
    warm = 2  # exclude compile + first-touch steps from the mean
    for step in range(1, args.steps + 1):
        t0 = time.perf_counter()
        exe.run(main_prog, feed={"x": xs, "y": ys},
                fetch_list=[loss.name])
        mgr.maybe_save(exe, main_prog, step)
        ms = (time.perf_counter() - t0) * 1e3
        if step > warm:
            step_ms.append(ms)
    mgr.wait()

    snap = _tm.snapshot()
    stall = _hist(snap, "checkpoint_save_stall_ms")
    write = _hist(snap, "checkpoint_write_ms")
    mean_step = float(np.mean(step_ms)) if step_ms else 0.0
    mean_stall = stall["sum"] / stall["count"] if stall["count"] else 0.0
    mean_write = write["sum"] / write["count"] if write["count"] else 0.0
    rec = {
        "mode": "sync" if args.sync else "async",
        "steps": args.steps,
        "saves": int(stall["count"]),
        "mean_step_ms": round(mean_step, 3),
        "mean_save_stall_ms": round(mean_stall, 3),
        "p99_save_stall_ms": round(stall["p99"], 3),
        "mean_write_ms": round(mean_write, 3),
        "stall_frac_of_step": round(mean_stall / mean_step, 4)
                              if mean_step else None,
        "overlap_drops": _tm.counter_total("checkpoint_save_overlap_total"),
    }
    print(json.dumps(rec, indent=1, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)

    if args.assert_stall_frac is not None:
        limit = args.assert_stall_frac * mean_step
        if mean_stall >= limit:
            print("FAIL: mean save stall %.3fms >= %.1f%% of mean step "
                  "%.3fms" % (mean_stall, 100 * args.assert_stall_frac,
                              mean_step), file=sys.stderr)
            return 1
        print("OK: mean save stall %.3fms < %.1f%% of mean step %.3fms"
              % (mean_stall, 100 * args.assert_stall_frac, mean_step))
    return 0


if __name__ == "__main__":
    sys.exit(main())
