"""Inspect paddle_tpu telemetry: pretty-print a dumped snapshot or scrape
a live pserver's ``__metrics__`` RPC.

Usage:
    python tools/metrics_dump.py --json  RUN_DIR/metrics.json
    python tools/metrics_dump.py --scrape HOST:PORT [--timeout SECS]
    python tools/metrics_dump.py ... --prom          # Prometheus text
    python tools/metrics_dump.py ... --raw           # raw JSON passthrough

``--json`` reads what ``telemetry.dump()`` / the Executor end-of-run hook
wrote under FLAGS_telemetry_dir; ``--scrape`` asks a running pserver
(distributed/ps.py publishes a fresh snapshot every round).  The default
output is a human table; --prom re-renders either source in Prometheus
exposition format for scrapers.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _filter_snap(snap, prefix):
    """Keep only metric families whose FLAT name starts with `prefix`
    (label suffixes ride along)."""
    kept = dict(snap)
    for fam in ("counters", "gauges", "histograms"):
        kept[fam] = {k: v for k, v in snap.get(fam, {}).items()
                     if k.startswith(prefix)}
    kept["events_logged"] = {k: v
                             for k, v in snap.get("events_logged",
                                                  {}).items()
                             if k.startswith(prefix)}
    kept["info"] = {}
    return kept


def render_table(snap, out=sys.stdout):
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    if counters:
        out.write("counters:\n")
        for k in sorted(counters):
            out.write("  %-52s %g\n" % (k, counters[k]))
    if gauges:
        out.write("gauges:\n")
        for k in sorted(gauges):
            out.write("  %-52s %g\n" % (k, gauges[k]))
    if hists:
        out.write("histograms (ms unless the name says otherwise):\n")
        for k in sorted(hists):
            h = hists[k]
            out.write("  %-40s n=%-6d sum=%-10g p50=%-8g p90=%-8g "
                      "p99=%g\n" % (k, h["count"], h["sum"], h["p50"],
                                    h["p90"], h["p99"]))
    ev = snap.get("events_logged", {})
    if ev:
        out.write("events logged: %s\n"
                  % ", ".join("%s=%d" % kv for kv in sorted(ev.items())))
    info = snap.get("info", {})
    if info:
        out.write("info payloads: %s\n" % ", ".join(sorted(info)))
    if not (counters or gauges or hists or ev):
        out.write("(empty snapshot — was FLAGS_telemetry on?)\n")


def render_fleet(doc, out=sys.stdout):
    """Human view of a ``__fleet__`` aggregate (serving/fleetmon.py):
    per-replica rows, fleet-merged histograms, windowed rates, goodput,
    and SLO burn state."""
    out.write("fleet @ t=%.3f epoch=%s replicas_up=%s\n"
              % (doc.get("t", 0.0), doc.get("epoch", "?"),
                 doc.get("replicas_up", "?")))
    for r in doc.get("replicas", []):
        p99 = r.get("p99_ms", {})
        out.write("  %-22s role=%-8s up=%-5s q=%-5g kv=%-5.2f "
                  "hit=%-5.2f server_p99=%-8g itl_p99=%g\n"
                  % (r.get("endpoint", "?"), r.get("role", "?"),
                     r.get("up"), r.get("queue_depth", 0.0),
                     r.get("kv_occupancy", 0.0),
                     r.get("prefix_hit_rate", 0.0),
                     p99.get("server_ms", 0.0), p99.get("itl_ms", 0.0)))
    hists = doc.get("histograms", {})
    if hists:
        out.write("fleet-merged histograms:\n")
        for k in sorted(hists):
            h = hists[k]
            out.write("  %-40s n=%-6d p50=%-8g p90=%-8g p99=%g\n"
                      % (k, h.get("count", 0), h.get("p50", 0.0),
                         h.get("p90", 0.0), h.get("p99", 0.0)))
    rates = doc.get("rates", {})
    if rates:
        out.write("windowed rates (/s over %gs):\n"
                  % doc.get("rate_window_s", 0.0))
        for k in sorted(rates):
            if rates[k]:
                out.write("  %-52s %g\n" % (k, rates[k]))
    gp = doc.get("goodput", {})
    if gp:
        out.write("goodput: %s\n"
                  % ", ".join("%s=%g" % kv for kv in sorted(gp.items())))
    for s in doc.get("slo", []):
        out.write("slo %-14s %s p%d obj=%gms burn fast=%.2f slow=%.2f "
                  "%s\n" % (s["name"], s["metric"],
                            round(s["quantile"] * 100),
                            s["objective_ms"], s["burn_fast"],
                            s["burn_slow"],
                            "FIRING" if s["active"] else "ok"))


def main(argv=None):
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--json", dest="json_path",
                     help="metrics.json written by telemetry.dump()")
    src.add_argument("--scrape", dest="endpoint",
                     help="live pserver HOST:PORT (__metrics__ RPC)")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="scrape connect/RPC deadline in seconds")
    ap.add_argument("--fleet", action="store_true", dest="fleet_doc",
                    help="with --scrape: GET the coordinator's merged "
                    "__fleet__ aggregate (serving/fleetmon.py) instead "
                    "of one replica's __metrics__ snapshot; with --json "
                    "render the file as a fleet doc (merged histograms "
                    "include migration_ms, rates include kv_migrate_*)")
    ap.add_argument("--prom", action="store_true",
                    help="emit Prometheus exposition text")
    ap.add_argument("--raw", action="store_true",
                    help="emit the raw JSON snapshot")
    ap.add_argument("--elastic", action="store_true",
                    help="show only the elastic re-quorum health metrics "
                    "(elastic_epoch/world gauges, eviction/rejoin "
                    "counters, re-quorum duration histogram)")
    ap.add_argument("--collective", action="store_true",
                    help="show only the collective-exchange metrics "
                    "(collective_nranks/wire_bytes gauges+counters and "
                    "the zero1_* shard accounting)")
    ap.add_argument("--compile", action="store_true", dest="compile_only",
                    help="show only compilation metrics: the two-tier "
                    "cache (compile_cache_* hit/miss/store/eviction/error "
                    "counters, load/store latency) and the executor's "
                    "trace/lower/XLA-compile breakdown")
    ap.add_argument("--kernels", action="store_true", dest="kernels_only",
                    help="show only Pallas kernel-adoption metrics: the "
                    "pallas_kernel_used_total{kernel} / "
                    "pallas_kernel_fallback_total{kernel,reason} counters "
                    "(pallas_kernels/adoption.py)")
    ap.add_argument("--serving", action="store_true", dest="serving_only",
                    help="show only inference-serving metrics: queue "
                    "depth / qps / fleet gauges, request / shed / timeout "
                    "/ batch counters, latency + batch-fill histograms, "
                    "plus the control plane — per-tier shed counters "
                    "(serving_tier_shed_total{tier}), autoscaler events "
                    "(autoscale_events_total{dir}), rollout_state gauge "
                    "and rollback counters, client shed retries, and "
                    "injected wire faults (serving/engine.py + fleet.py "
                    "+ rollout.py)")
    ap.add_argument("--decode", action="store_true", dest="decode_only",
                    help="show only autoregressive-decode metrics: paged "
                    "KV pool counters/gauges (kv_block_*, kv_blocks_in_use"
                    ", kv_cache_bytes, kv_block_evictions_total), "
                    "serving_decode_* / serving_tokens_generated_total, "
                    "speculative-decode spec_* counters and acceptance "
                    "histogram, prefix_cache_* hit/publish/eviction "
                    "counters, the decode_batch_occupancy histogram, "
                    "disaggregated sealed-block transfer counters "
                    "(kv_xfer_*, serving_handoff_fallback_total), live "
                    "session-migration counters and timing (kv_migrate_*"
                    ", migration_ms, client_resume/*follow/*dup) and the "
                    "kv_pool_occupancy / prefix_cache_hit_rate gauges")
    ap.add_argument("--tracing", action="store_true", dest="tracing_only",
                    help="show only distributed-tracing health metrics: "
                    "tracing_records_total{kind} and "
                    "tracing_flightrec_dumps_total{reason} "
                    "(core/tracing.py)")
    ap.add_argument("--checkpoint", action="store_true", dest="ckpt_only",
                    help="show only checkpoint I/O metrics: the "
                    "checkpoint_save_stall_ms vs checkpoint_write_ms "
                    "split, restore timings/sources "
                    "(checkpoint_restore_source_total{source}), overlap "
                    "drops, temp-GC sweeps, and the executor's D2H "
                    "snapshot histogram (io.py + core/executor.py)")
    ap.add_argument("--lint", action="store_true", dest="lint_only",
                    help="show only static-checker metrics: per-rule "
                    "static_check_warnings counters, the whole-world "
                    "verifier's static_check_world_* run/finding counters "
                    "and rank/peak-HBM gauges, and the concurrency "
                    "lint's static_check_concurrency_total / "
                    "static_check_waivers_total per-rule counters")
    args = ap.parse_args(argv)

    if args.json_path:
        with open(args.json_path) as f:
            snap = json.load(f)
    elif args.fleet_doc:
        from paddle_tpu import telemetry
        from paddle_tpu.serving.fleetmon import FLEET_RPC_KEY

        snap = telemetry.scrape(args.endpoint, timeout=args.timeout,
                                key=FLEET_RPC_KEY)
    else:
        from paddle_tpu import telemetry

        snap = telemetry.scrape(args.endpoint, timeout=args.timeout)

    if args.fleet_doc:
        if args.raw:
            json.dump(snap, sys.stdout, indent=1)
            sys.stdout.write("\n")
        else:
            render_fleet(snap)
        return 0

    if args.elastic:
        snap = _filter_snap(snap, "elastic_")
    if args.collective:
        # str.startswith takes a tuple: both metric families in one pass
        snap = _filter_snap(snap, ("collective_", "zero1_"))
    if args.compile_only:
        snap = _filter_snap(snap, ("compile_cache_", "executor_compile",
                                   "executor_xla_", "executor_trace_",
                                   "executor_cache_", "executor_aot_",
                                   "executor_warmup"))
    if args.kernels_only:
        snap = _filter_snap(snap, "pallas_kernel_")
    if args.serving_only:
        # serving_* plus the PR 16 control-plane families (autoscaler,
        # rollout gate, client shed retries, injected wire faults)
        snap = _filter_snap(snap, ("serving_", "autoscale_", "rollout_",
                                   "client_shed_", "fault_injected_"))
    if args.decode_only:
        snap = _filter_snap(snap, ("kv_block", "kv_cache_",
                                   "kv_blocks_in_use", "serving_decode_",
                                   "serving_tokens_", "serving_abort_",
                                   "decode_batch_occupancy", "spec_",
                                   "prefix_cache_", "kv_xfer_", "kv_pool_",
                                   "serving_handoff_", "kv_migrate_",
                                   "migration_ms", "client_resume_",
                                   "client_migrate_", "client_stream_"))
    if args.tracing_only:
        snap = _filter_snap(snap, "tracing_")
    if args.ckpt_only:
        # checkpoint_* covers save/write/restore/overlap/tmp-GC; the D2H
        # snapshot cost lives under the executor family
        snap = _filter_snap(snap, ("checkpoint_", "executor_snapshot"))
    if args.lint_only:
        # covers static_check_warnings{rule=}, static_check_world_*, and
        # the threadlint static_check_concurrency_total /
        # static_check_waivers_total families
        snap = _filter_snap(snap, "static_check")

    if args.raw:
        json.dump(snap, sys.stdout, indent=1)
        sys.stdout.write("\n")
    elif args.prom:
        from paddle_tpu import telemetry

        sys.stdout.write(telemetry.prometheus_text(snap))
    else:
        render_table(snap)
    return 0


if __name__ == "__main__":
    sys.exit(main())
