// Standalone C++ training demo (parity: the reference's
// paddle/fluid/train/demo/demo_trainer.cc — load a saved program, run the
// train loop from C++ with no Python script).
//
// Usage: demo_trainer <model_dir> <repo_root> [steps] [place]
//   model_dir: directory written by fluid.io.save_train_model(...)
//   repo_root: directory containing paddle_tpu/ (for the embedded runtime)
//
// The model is the synthetic 5-class classification task: feeds "x"
// [64, 20] float32 drawn around one of 5 fixed centers and "y" [64, 1]
// int64 labels; fetches the loss.  Prints one loss per step; exits 0 iff
// the final loss is below 0.25 (training worked end-to-end).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <vector>

extern "C" {
void PT_Init(const char* repo_root);
int64_t PT_NumOps();
int64_t PT_TrainerCreate(const char* model_dir, const char* place);
int PT_Feed(int64_t handle, const char* name, const void* data,
            const char* dtype, const int64_t* dims, int ndim);
double PT_TrainerStep(int64_t handle);
int PT_Destroy(int64_t handle);
}

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <model_dir> <repo_root> [steps] [place]\n",
                 argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  const char* repo_root = argv[2];
  const int steps = argc > 3 ? std::atoi(argv[3]) : 40;
  const char* place = argc > 4 ? argv[4] : "cpu";

  PT_Init(repo_root);
  std::printf("registered ops: %lld\n",
              static_cast<long long>(PT_NumOps()));

  int64_t t = PT_TrainerCreate(model_dir, place);
  if (t <= 0) {
    std::fprintf(stderr, "failed to load train model from %s\n", model_dir);
    return 1;
  }

  constexpr int B = 64, D = 20, K = 5;
  std::mt19937 rng(0);
  std::normal_distribution<float> gauss(0.f, 1.f);
  std::uniform_int_distribution<int> pick(0, K - 1);

  // fixed class centers
  std::vector<float> centers(K * D);
  for (auto& c : centers) c = 3.f * gauss(rng);

  std::vector<float> x(B * D);
  std::vector<int64_t> y(B);
  double loss = 1e30;
  for (int s = 0; s < steps; ++s) {
    for (int b = 0; b < B; ++b) {
      int k = pick(rng);
      y[b] = k;
      for (int d = 0; d < D; ++d) {
        x[b * D + d] = centers[k * D + d] + gauss(rng);
      }
    }
    const int64_t xdims[2] = {B, D};
    const int64_t ydims[2] = {B, 1};
    if (PT_Feed(t, "x", x.data(), "float32", xdims, 2) != 0 ||
        PT_Feed(t, "y", y.data(), "int64", ydims, 2) != 0) {
      std::fprintf(stderr, "FAIL: feed error at step %d\n", s);
      return 1;
    }
    loss = PT_TrainerStep(t);
    std::printf("step %d loss %.6f\n", s, loss);
    if (!std::isfinite(loss)) {
      std::fprintf(stderr, "FAIL: step %d returned non-finite loss\n", s);
      return 1;
    }
  }
  PT_Destroy(t);

  if (!(loss < 0.25)) {
    std::fprintf(stderr, "FAIL: final loss %.4f >= 0.25\n", loss);
    return 1;
  }
  std::printf("PASS\n");
  return 0;
}
