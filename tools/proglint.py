#!/usr/bin/env python
"""proglint: standalone static Program verifier CLI (core/analysis.py).

Runs the four rule families (well-formedness, type/shape flow,
donation/aliasing hazards, distributed lint) over a saved inference model
or the bundled model zoo, and prints structured diagnostics.

    # lint a saved inference model directory (__model__.json)
    python tools/proglint.py --model /path/to/saved_model

    # lint every bundled model (main + startup programs)
    python tools/proglint.py

    # one model, with the annotated text op-graph
    python tools/proglint.py --builtin mnist_mlp --dump

    # also lint grad programs and a transpiled 2-pserver split
    python tools/proglint.py --grad --transpile 2

    # whole-world check: materialize every rank of an 8-device 4x2
    # (dp x tp) world, match collective schedules across ranks
    # (DL101-DL104) and report the static per-replica peak-HBM
    # estimate (MEM001-MEM003)
    python tools/proglint.py --world 8 --mesh 4x2

    # same, over the ZeRO-1 int8-wire collective path with a budget
    python tools/proglint.py --world 4 --zero1 --mem-budget 8e9

Exit status: 0 when clean, 1 when any error- or warning-severity
diagnostic was found (info findings are advisory; --strict makes them
fail too).  The run_ci.sh --lint leg runs this with
FLAGS_static_check=error over all bundled models.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="proglint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", metavar="DIR",
                    help="saved inference model directory (__model__.json)")
    ap.add_argument("--builtin", action="append", metavar="NAME",
                    help="bundled model to lint (repeatable; default all)")
    ap.add_argument("--list", action="store_true",
                    help="list bundled model names and exit")
    ap.add_argument("--grad", action="store_true",
                    help="also lint grad programs (append_backward on "
                    "builders that do not already include an optimizer)")
    ap.add_argument("--transpile", type=int, default=0, metavar="N",
                    help="also lint each trainable model transpiled onto "
                    "N pservers (placement/pairing/duplication rules)")
    ap.add_argument("--dump", action="store_true",
                    help="print the annotated text op-graph per program")
    ap.add_argument("--strict", action="store_true",
                    help="info-severity findings also fail the run")
    ap.add_argument("--world", type=int, default=0, metavar="N",
                    help="materialize every rank of an N-device world and "
                    "run the cross-rank collective-schedule + peak-HBM "
                    "checks (DL101-DL104, MEM001-MEM003)")
    ap.add_argument("--mesh", metavar="DPxTP", default=None,
                    help="world layout as dpxtp, e.g. 4x2 (default Nx1); "
                    "dp is the collective world, tp shards within a rank")
    ap.add_argument("--zero1", action="store_true",
                    help="verify the ZeRO-1 sharded collective path "
                    "(int8 wire) instead of plain allreduce")
    ap.add_argument("--mem-budget", type=float, default=0, metavar="BYTES",
                    help="per-replica HBM budget for the static estimator; "
                    "a predicted peak above this is a MEM003 error")
    ap.add_argument("--batch", type=int, default=32, metavar="B",
                    help="batch size assumed for -1 dims in the static "
                    "peak-HBM estimate (default 32)")
    ap.add_argument("--seed-defect", choices=["dl101"], default=None,
                    help="self-test: drop the last rank's first "
                    "collective from its materialized program before "
                    "matching — must be reported as DL101 with that "
                    "rank and op index (verifies the checker detects "
                    "a rank-divergent schedule end to end)")
    args = ap.parse_args(argv)

    mesh = None
    if args.mesh:
        try:
            dp, tp = (int(p) for p in args.mesh.lower().split("x"))
        except ValueError:
            ap.error("--mesh wants DPxTP, e.g. 4x2; got %r" % args.mesh)
        mesh = (dp, tp)
        if not args.world:
            args.world = dp * tp
    if args.world and mesh is None:
        mesh = (args.world, 1)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as fluid
    from paddle_tpu import debugger, models
    from paddle_tpu.core import analysis
    from paddle_tpu.framework import OP_ROLE_KEY, OpRole, Program

    builders = models.bundled_builders()
    if args.list:
        print("\n".join(sorted(builders)))
        return 0

    failed = [0]

    def check(rep, program=None):
        print(rep.format())
        bad = len(rep.errors) + len(rep.warnings)
        if args.strict:
            bad += len(rep.infos)
        failed[0] += bad
        if args.dump and program is not None:
            print(debugger.draw_program(program, rep.diagnostics))

    if args.model:
        path = os.path.join(args.model, "__model__.json")
        with open(path) as f:
            bundle = json.load(f)
        program = Program.from_dict(bundle["program"])
        check(analysis.verify_program(
            program, bundle.get("feed_names", ()),
            bundle.get("fetch_names", ()), label=args.model), program)
        return 1 if failed[0] else 0

    names = args.builtin or sorted(builders)
    unknown = [n for n in names if n not in builders]
    if unknown:
        ap.error("unknown builtin model(s) %s (have: %s)"
                 % (unknown, ", ".join(sorted(builders))))

    for name in names:
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            feeds, fetches = builders[name]()
        has_backward = any(
            int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Backward
            for op in main_p.global_block().ops)
        if args.grad and not has_backward:
            with fluid.program_guard(main_p, startup_p):
                fluid.backward.append_backward(fetches[0])
        feed_names = [v.name for v in feeds]
        fetch_names = [v.name for v in fetches]
        check(analysis.verify_program(main_p, feed_names, fetch_names,
                                      label=name), main_p)
        check(analysis.verify_program(startup_p, label=name + "/startup"),
              startup_p)

        has_optimize = any(
            int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Optimize
            for op in main_p.global_block().ops)
        if args.transpile > 0 and has_optimize:
            eps = ",".join("127.0.0.1:%d" % (6174 + i)
                           for i in range(args.transpile))
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=0, program=main_p, pservers=eps,
                        trainers=2, startup_program=startup_p)
            check(analysis.verify_transpiled(t._ps_state))
            trainer_p = t.get_trainer_program()
            check(analysis.verify_program(
                trainer_p, feed_names, fetch_names,
                label=name + "/ps-trainer"), trainer_p)
            for ep in eps.split(","):
                check(analysis.verify_program(
                    t.get_pserver_program(ep),
                    label="%s/pserver %s" % (name, ep)))

        if args.world > 0:
            from paddle_tpu.core import world_analysis
            # rebuild fresh: --transpile may have rewritten main_p in
            # place, and inference-only builders need a grad graph
            # before the collective transpiler has anything to rewrite
            wmain, wstartup = fluid.Program(), fluid.Program()
            with fluid.program_guard(wmain, wstartup):
                _, wfetches = builders[name]()
                if not any(int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Optimize
                           for op in wmain.global_block().ops):
                    fluid.optimizer.SGD(learning_rate=0.01).minimize(
                        wfetches[0])
            actual = None
            if args.seed_defect == "dl101":
                # materialize under the same collective mode verify_world
                # will use, or the seeded rank diverges for the wrong
                # reason (mode mismatch instead of the dropped op)
                overrides = {"FLAGS_collective_mode": "zero1",
                             "FLAGS_allreduce_dtype": "int8"} \
                    if args.zero1 else {}
                saved = fluid.get_flags(list(overrides))
                fluid.set_flags(overrides)
                try:
                    worlds = world_analysis.materialize_world(
                        wmain, wstartup, mesh[0])
                finally:
                    fluid.set_flags(saved)
                tm, ts = worlds[mesh[0] - 1]
                tb = tm.global_block()
                drop = next(
                    (i for i, op in enumerate(tb.ops)
                     if op.type.startswith("c_allgather")),
                    next(i for i, op in enumerate(tb.ops)
                         if op.type in world_analysis._COLLECTIVE_OPS))
                print("%s: seeded defect — dropped %s at op %d from "
                      "rank %d" % (name, tb.ops[drop].type, drop,
                                   mesh[0] - 1))
                del tb.ops[drop]
                actual = {mesh[0] - 1: (tm, ts)}
            check(world_analysis.verify_world(
                wmain, wstartup, mesh[0],
                mesh=mesh,
                declared_world=args.world,
                actual=actual,
                feed_names=feed_names, fetch_names=fetch_names,
                batch=args.batch,
                mem_budget=int(args.mem_budget) or None,
                collective_mode="zero1" if args.zero1 else None,
                wire_dtype="int8" if args.zero1 else None,
                label="%s world %d mesh %dx%d%s"
                      % (name, args.world, mesh[0], mesh[1],
                         " zero1" if args.zero1 else "")))

    print("proglint: %s" % ("FAIL (%d finding(s))" % failed[0]
                            if failed[0] else "PASS"))
    return 1 if failed[0] else 0


if __name__ == "__main__":
    sys.exit(main())
