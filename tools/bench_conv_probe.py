"""Probe: isolated conv efficiency at ResNet-50 shapes (fwd + wgrad).

The train-step profile shows 164 conv-containing fusions at ~19% average
MXU efficiency.  This measures each conv class alone (barrier-chained,
host-fetch sync) to separate "convs are slow on this chip" from "the
fused epilogues slow the convs down".
"""


import jax
import jax.numpy as jnp
import numpy as np
import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from bench_util import timed as _time, tunnel_rtt as _rtt
from jax import lax

REP = 64


def conv_chain(x, w, stride, rep):
    def body(c, _):
        xb, cb = lax.optimization_barrier((x, c))
        y = lax.conv_general_dilated(
            xb, w, (stride, stride),
            [((w.shape[2] - 1) // 2,) * 2, ((w.shape[3] - 1) // 2,) * 2],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.bfloat16)
        yb = lax.optimization_barrier(y)  # forces full materialization:
        # a bare slice lets XLA compute one output pixel (slice-of-conv)
        return yb.reshape(-1)[0].astype(jnp.float32) * 1e-9 + cb * 0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), None, length=rep)
    return (out,)


def wgrad_chain(x, dy, kh, stride, rep):
    # weight gradient as lax conv: contract over batch (the fused
    # copy_subtract/multiply_subtract wgrad fusions in the step profile)
    def body(c, _):
        xb, cb = lax.optimization_barrier((x, c))
        dw = lax.conv_general_dilated(
            xb, dy, window_strides=(1, 1),
            padding=[((kh - 1) // 2,) * 2, ((kh - 1) // 2,) * 2],
            lhs_dilation=(1, 1), rhs_dilation=(stride, stride),
            dimension_numbers=("CNHW", "IOHW", "CNHW"),
            preferred_element_type=jnp.float32)
        dwb = lax.optimization_barrier(dw)
        return dwb.reshape(-1)[0] * 1e-9 + cb * 0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), None, length=rep)
    return (out,)


def main():
    rtt = _rtt()
    print(f"device: {jax.devices()[0]}  RTT {rtt*1e3:.1f} ms")
    key = jax.random.PRNGKey(0)
    N = 512
    cases = [
        ("conv1 7x7s2 3->64 @224", (N, 3, 224, 224), (64, 3, 7, 7), 2),
        ("1x1 256->64 @56", (N, 256, 56, 56), (64, 256, 1, 1), 1),
        ("3x3 64->64 @56", (N, 64, 56, 56), (64, 64, 3, 3), 1),
        ("1x1 64->256 @56", (N, 64, 56, 56), (256, 64, 1, 1), 1),
        ("3x3 128->128 @28", (N, 128, 28, 28), (128, 128, 3, 3), 1),
        ("1x1 1024->256 @14", (N, 1024, 14, 14), (256, 1024, 1, 1), 1),
        ("3x3 512->512 @7", (N, 512, 7, 7), (512, 512, 3, 3), 1),
    ]
    for name, xs, ws, stride in cases:
        x = jax.random.normal(key, xs, jnp.bfloat16)
        w = jax.random.normal(key, ws, jnp.bfloat16) * 0.05
        oh = xs[2] // stride
        fl = 2 * N * ws[0] * ws[1] * ws[2] * ws[3] * oh * oh
        t = _time(lambda x, w, s=stride: conv_chain(x, w, s, REP), x, w)
        dev = max(t - rtt, 1e-9) / REP
        print(f"fwd  {name:26s} {dev*1e3:7.3f} ms  {fl/dev/1e12:6.1f} TF/s"
              f"  ({fl/1e9:.1f} GF)")
        # wgrad: dy has the output shape
        dy = jax.random.normal(key, (N, ws[0], oh, oh), jnp.bfloat16)
        t = _time(lambda x, dy, k=ws[2], s=stride: wgrad_chain(
            x, dy, k, s, REP), x, dy)
        dev = max(t - rtt, 1e-9) / REP
        print(f"wgrd {name:26s} {dev*1e3:7.3f} ms  {fl/dev/1e12:6.1f} TF/s")


if __name__ == "__main__":
    main()
