"""Probe: 4D BN-stats reduce bandwidth by layout + chained matmul peak.

Follow-up to bench_reduce_pallas.py: the round-2 roofline (60-76 GB/s
reduce cap / 128-147 GB/s stream / 83 TF/s matmul peak) was a per-call-RTT
artifact.  Protocol here: lax.scan chains with lax.optimization_barrier on
the loop-invariant operand (defeats hoisting/algebraic elision — plain
scalar-add carries got simplified away: slice-of-dot, (x+c)^2 expansion),
host-fetch sync, RTT subtracted, REP sized so device time >> RTT noise.
"""


import jax
import jax.numpy as jnp
import numpy as np
import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from bench_util import timed as _time, tunnel_rtt as _rtt
from jax import lax


def stats4d(x, axes, rep):
    def body(c, _):
        xb, cb = lax.optimization_barrier((x, c))
        xf = xb.astype(jnp.float32)
        s = jnp.sum(xf, axis=axes)
        ss = jnp.sum(xf * xf, axis=axes)
        return (jnp.sum(s) + jnp.sum(ss)) * 1e-12 + cb * 0.0, ()

    out, _ = lax.scan(body, jnp.float32(0.0), None, length=rep)
    return (out,)


def stream(x, rep):
    def body(y, _):
        yb = lax.optimization_barrier(y)
        return yb * jnp.bfloat16(1.0000001), ()

    y, _ = lax.scan(body, x, None, length=rep)
    return (y.reshape(-1)[0].astype(jnp.float32), y)


def matmul_chain(a, b, rep):
    def body(y, _):
        ab, yb = lax.optimization_barrier((a, y))
        return jnp.dot(ab + yb.reshape(-1)[0] * 0, b), ()

    y, _ = lax.scan(body, jnp.zeros_like(a), None, length=rep)
    return (y.reshape(-1)[0].astype(jnp.float32), y)


def main():
    rtt = _rtt()
    print(f"device: {jax.devices()[0]}  RTT {rtt*1e3:.1f} ms")
    key = jax.random.PRNGKey(0)

    REP = 256
    for name, shape, axes in [
        ("NCHW [512,64,56,56] red(0,2,3)", (512, 64, 56, 56), (0, 2, 3)),
        ("NHWC [512,56,56,64] red(0,1,2)", (512, 56, 56, 64), (0, 1, 2)),
        ("NCHW [512,256,28,28]", (512, 256, 28, 28), (0, 2, 3)),
        ("NHWC [512,28,28,256]", (512, 28, 28, 256), (0, 1, 2)),
        ("NCHW [512,2048,7,7]", (512, 2048, 7, 7), (0, 2, 3)),
        ("NHWC [512,7,7,2048]", (512, 7, 7, 2048), (0, 1, 2)),
    ]:
        x = jax.random.normal(key, shape, dtype=jnp.bfloat16)
        t = _time(lambda x, a=axes: stats4d(x, a, REP), x)
        nb = int(np.prod(shape)) * 2 * REP
        dev = max(t - rtt, 1e-9)
        print(f"{name:34s} {dev*1e3/REP:7.3f} ms/pass "
              f"{nb/dev/1e9:7.1f} GB/s")

    x = jax.random.normal(key, (1605632, 64), dtype=jnp.bfloat16)
    t = _time(lambda x: stream(x, REP), x)
    dev = max(t - rtt, 1e-9)
    nb = 1605632 * 64 * 2 * REP * 2
    print(f"{'stream 1r1w [1605632,64]':34s} {dev*1e3/REP:7.3f} ms/pass "
          f"{nb/dev/1e9:7.1f} GB/s")

    for n in (4096, 8192):
        a = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)
        b = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)
        t = _time(lambda a, b, n=n: matmul_chain(a, b, 32), a, b)
        dev = max(t - rtt, 1e-9)
        fl = 2 * n**3 * 32
        print(f"matmul {n}^3 bf16{'':18s} {dev*1e3/32:7.3f} ms/pass "
              f"{fl/dev/1e12:7.1f} TF/s")


if __name__ == "__main__":
    main()
