"""Manage the persistent two-tier compilation cache (core/compile_cache.py).

Usage:
    python tools/compile_cache.py stats   [--dir DIR] [--json]
    python tools/compile_cache.py ls      [--dir DIR]
    python tools/compile_cache.py clear   [--dir DIR]
    python tools/compile_cache.py prewarm [--dir DIR] --model NAME
                                          [--model NAME ...] [--batch N]

``stats``/``ls`` inspect the tier-B AOT entries (plus the tier-A XLA file
footprint); ``clear`` wipes both tiers.  ``prewarm`` builds bundled models
from ``models.bundled_builders()`` (the same zoo tools/proglint.py lints)
and runs ``Executor.warmup`` on each, so a later process — a trainer, an
elastic re-quorum, a serving bucket — starts with its executables already
on disk and pays a restore instead of an XLA compile.

The cache location comes from FLAGS_compile_cache_dir (env) or --dir.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _human(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return ("%.1f%s" if unit != "B" else "%d%s") % (n, unit)
        n /= 1024.0


def cmd_stats(cc, args):
    st = cc.stats()
    if args.json:
        json.dump(st, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 0
    print("cache dir : %s%s" % (st["dir"] or "(unset)",
                                "" if st["enabled"] else "  [disabled]"))
    print("tier B    : %d entries (%d valid), %s / cap %s"
          % (st["aot_entries"], st["aot_valid"], _human(st["aot_bytes"]),
             _human(st["max_bytes"])))
    print("tier A    : %d XLA files, %s" % (st["xla_files"],
                                            _human(st["xla_bytes"])))
    return 0


def cmd_ls(cc, args):
    ents = cc.entries()
    if not ents:
        print("(no tier-B entries under %s)" % (cc.cache_dir() or "(unset)"))
        return 0
    print("%-14s %-9s %-6s %-12s %-19s meta" % ("key", "bytes", "valid",
                                                "jax", "last_used"))
    for r in ents:
        print("%-14s %-9s %-6s %-12s %-19s %s"
              % (r["key"][:12] + "..", _human(r["bytes"]),
                 "ok" if r["valid"] else "BAD", r["jax"] or "?",
                 time.strftime("%Y-%m-%d %H:%M:%S",
                               time.localtime(r["last_used"])),
                 json.dumps(r["meta"], sort_keys=True)))
    return 0


def cmd_clear(cc, args):
    st = cc.stats()
    cc.clear()
    print("cleared %d tier-B entries (%s) + %d tier-A files (%s) under %s"
          % (st["aot_entries"], _human(st["aot_bytes"]), st["xla_files"],
             _human(st["xla_bytes"]), cc.cache_dir()))
    return 0


def cmd_prewarm(cc, args):
    import paddle_tpu as fluid
    from paddle_tpu import models

    if not cc.enabled():
        print("error: no cache dir (set FLAGS_compile_cache_dir or --dir)",
              file=sys.stderr)
        return 2
    builders = models.bundled_builders()
    names = args.model or sorted(builders)
    unknown = [n for n in names if n not in builders]
    if unknown:
        print("error: unknown model(s) %s (have: %s)"
              % (unknown, ", ".join(sorted(builders))), file=sys.stderr)
        return 2
    rc = 0
    for name in names:
        t0 = time.perf_counter()
        with fluid.unique_name.guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 1
            with fluid.program_guard(main, startup):
                feeds, fetches = builders[name]()
        specs = {}
        for v in feeds:
            shape = tuple(args.batch if d == -1 else int(d)
                          for d in v.shape)
            specs[v.name] = (shape, v.dtype)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            try:
                got = exe.warmup(main, feed_specs=specs,
                                 fetch_list=[v.name for v in fetches])
            except Exception as e:
                print("%-18s FAILED: %s" % (name, e), file=sys.stderr)
                rc = 1
                continue
        print("%-18s %-8s key=%s.. %.0fms"
              % (name, got["source"], (got.get("key") or "?")[:12],
                 (time.perf_counter() - t0) * 1e3))
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="inspect / manage the persistent compilation cache")
    ap.add_argument("--dir", help="cache directory (overrides "
                    "FLAGS_compile_cache_dir)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("stats").add_argument("--json", action="store_true",
                                         help="machine-readable stats")
    sub.add_parser("ls")
    sub.add_parser("clear")
    pw = sub.add_parser("prewarm")
    pw.add_argument("--model", action="append", metavar="NAME",
                    help="bundled model to pre-compile (repeatable; "
                    "default all of models.bundled_builders())")
    pw.add_argument("--batch", type=int, default=8,
                    help="batch substituted for -1 feed dims (default 8)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.dir:
        os.environ["FLAGS_compile_cache_dir"] = args.dir
    import paddle_tpu as fluid  # noqa: F401  (flags read env at import)
    from paddle_tpu.core import compile_cache as cc

    if args.dir:
        fluid.set_flags({"FLAGS_compile_cache_dir": args.dir})
    return {"stats": cmd_stats, "ls": cmd_ls, "clear": cmd_clear,
            "prewarm": cmd_prewarm}[args.cmd](cc, args)


if __name__ == "__main__":
    sys.exit(main())
